"""Socket ingest plane for out-of-process agents — the process boundary
of SURVEY §2.3 P8 (the reference's kernel↔userspace perf-buffer seam,
re-drawn as agent↔service).

INTEGRATION.md's contract is "ship the event dtypes as raw bytes over
any transport"; this is that transport: a length-prefixed binary frame
protocol over a unix or TCP socket that a C/C++/Go agent can emit with
one writev per batch and zero serialization (numpy structured arrays are
fixed-layout).

Frame layout (little-endian, 16-byte header):

    u32 magic   = 0x414C5A31  ("ALZ1")
    u8  kind    = 1 l7 | 2 tcp | 3 proc | 4 native (AlzRecord rows)
    u8  tenant  = tenant id (ISSUE 14); 0 = the primary/legacy tenant
    u8  _pad[2]
    u32 count   = number of records
    u32 length  = payload bytes (must equal count * itemsize)
    ...payload  = `count` packed records of the kind's dtype

The tenant byte occupies what was header padding, which legacy agents
zero-fill — so a pre-tenancy frame IS a tenant-0 frame byte for byte
and recorded traces replay unchanged. Frames route to the service's
per-tenant ingest partition (``submit_*(…, tenant=)``); a tenant id the
service has no partition for is refused at the door — its rows land in
the service's dedicated REFUSED ledger (cause ``filtered``, surfaced as
``degraded_snapshot()["refused"]`` + ``ingest.unknown_tenant``), never
in any tenant's conservation books and never silently folded into
another tenant's stream. The byte is unauthenticated like
the rest of the header: deployments multiplexing mutually untrusted
fleets must terminate per-tenant transport (one socket per fleet, or a
TLS sidecar) in front of this listener.

kind 4 bypasses the aggregator: records are the 32-byte AlzRecord wire
format (graph/native.py) for pre-attributed edges pushed straight at the
windowed graph store — the "native fast path" of INTEGRATION.md over a
socket instead of in-process ctypes.

Malformed frames QUARANTINE instead of killing the connection (ISSUE 6,
ARCHITECTURE §3j): a frame whose header parses but whose payload is
inconsistent (count*itemsize != length, unknown kind) is counted and
skipped — the framing is intact, so the stream just continues. A frame
whose HEADER is garbage (bad magic, absurd length) means framing is
lost: the reader resyncs by scanning the byte stream for the next frame
magic and resumes there. A healthy agent behind one corrupted frame
keeps its connection; rows in quarantined frames land in the service's
drop ledger (cause ``quarantined``) when their count is readable.
Backpressure follows the service contract: submit_* drop-not-block, so
a flooding agent loses events rather than stalling the socket reader
into TCP backpressure.
"""

from __future__ import annotations

import socket
import struct
import threading
from pathlib import Path
from typing import Optional

import numpy as np

from alaz_tpu.events.schema import (
    L7_EVENT_DTYPE,
    PROC_EVENT_DTYPE,
    TCP_EVENT_DTYPE,
)
from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.ingest_server")

MAGIC = 0x414C5A31
# Public: the 16-byte frame header IS the wire contract out-of-process
# agents compile against (agent_example.cc FrameHeader). alazspec pins
# its size/format in resources/specs/wire_layouts.json (ALZ021). The
# tenant byte (ISSUE 14) sits in the old pad region: same 16 bytes,
# legacy zero-filled frames parse as tenant 0.
FRAME_HEADER = struct.Struct("<IBB2xII")

KIND_L7 = 1
KIND_TCP = 2
KIND_PROC = 3
KIND_NATIVE = 4

_KIND_DTYPE = {
    KIND_L7: L7_EVENT_DTYPE,
    KIND_TCP: TCP_EVENT_DTYPE,
    KIND_PROC: PROC_EVENT_DTYPE,
}

MAX_FRAME_BYTES = 64 * 1024 * 1024  # one frame must fit in memory comfortably

# the 4 magic bytes as they appear on the wire (little-endian), the
# resync scanner's needle
_MAGIC_BYTES = struct.pack("<I", MAGIC)

# per-connection garbage budgets: quarantine/resync keep a healthy
# agent's stream alive through the occasional corrupted frame, but an
# agent streaming endless garbage is hostile or broken — past either
# budget the connection drops (the pre-ISSUE-6 defense, restored with
# margins). Bytes bound the unframeable-garbage scan; the frame count
# bounds the well-framed-but-malformed flood (valid magic/length,
# inconsistent count or unknown kind), which never touches the scanner.
MAX_RESYNC_BYTES_PER_CONN = 16 * 1024 * 1024
MAX_QUARANTINED_FRAMES_PER_CONN = 64


def pack_frame(kind: int, batch: np.ndarray, tenant: int = 0) -> bytes:
    """Client-side helper: one event batch → one wire frame. ``tenant``
    names the fleet this batch belongs to (0 = primary/legacy)."""
    from alaz_tpu.events.schema import MAX_TENANTS

    if not 0 <= tenant < MAX_TENANTS:
        raise ValueError(f"tenant must be in [0, {MAX_TENANTS}); got {tenant}")
    payload = np.ascontiguousarray(batch).tobytes()
    return (
        FRAME_HEADER.pack(MAGIC, kind, tenant, batch.shape[0], len(payload))
        + payload
    )


class IngestServer:
    """Accepts agent connections and feeds their frames into a Service.

    ``path`` starts a unix-domain listener; ``port`` a TCP one (use the
    loopback/TLS-terminating sidecar of your deployment for anything
    off-host — the reference's log streamer does the same)."""

    def __init__(
        self,
        service,
        path: str | Path | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.service = service
        self.frames = 0  # guarded-by: self._state_lock
        self.records = 0  # guarded-by: self._state_lock
        self.bad_frames = 0  # guarded-by: self._state_lock
        self.unsupported_frames = 0  # guarded-by: self._state_lock
        # ISSUE 6 quarantine/resync plane: frames rejected while keeping
        # the connection, resync scans performed, and garbage bytes
        # skipped while hunting for the next frame magic
        self.quarantined_frames = 0  # guarded-by: self._state_lock
        self.resyncs = 0  # guarded-by: self._state_lock
        self.resync_bytes = 0  # guarded-by: self._state_lock
        # rows in quarantined frames attribute to the service's unified
        # drop ledger when it has one (and their count field is readable)
        self._ledger = getattr(service, "ledger", None)
        self._state_lock = threading.Lock()
        self._stop = threading.Event()
        # the accept loop rebinds/appends while stop() iterates — the
        # list must live under the state lock (alazlint ALZ010 finding,
        # fixed in ISSUE 2: a join missed mid-rebind leaked the thread)
        self._threads: list[threading.Thread] = []  # guarded-by: self._state_lock
        self._unix_path: Optional[Path] = None
        if path is not None:
            self._unix_path = Path(path)
            self.address: str | tuple = str(path)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            if self._unix_path.exists():
                # A stale socket file from a previous run blocks bind —
                # but only unlink if nothing answers: silently stealing a
                # LIVE instance's listener would redirect its agents here.
                probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    probe.settimeout(0.5)
                    probe.connect(str(path))
                except (ConnectionRefusedError, FileNotFoundError):
                    # nothing accepting: stale file from a dead process.
                    # missing_ok: a concurrently-restarting sibling may
                    # have reclaimed it first — bind() then reports the
                    # conflict cleanly
                    self._unix_path.unlink(missing_ok=True)
                except (socket.timeout, BlockingIOError):
                    # a full backlog on a stalled-but-live listener shows
                    # as EAGAIN (BlockingIOError; AF_UNIX connect under
                    # settimeout is non-blocking) or as a timeout —
                    # ambiguity must favor NOT stealing
                    self._sock.close()
                    raise OSError(
                        f"ingest socket {path} did not answer a connect "
                        "probe but may be live (backlog full?); refusing "
                        "to steal it — remove the file manually if stale"
                    ) from None
                else:
                    self._sock.close()
                    raise OSError(
                        f"ingest socket {path} is in use by a live process; "
                        "refusing to steal its listener"
                    )
                finally:
                    probe.close()
            self._sock.bind(str(path))
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.bind((host, port))
            self.address = self._sock.getsockname()
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        # KIND_NATIVE needs the C++ ring (push_records); the numpy store
        # doesn't speak the wire record format
        store = getattr(service, "graph_store", None)
        self._native_store = store if hasattr(store, "push_records") else None
        # separate warn-once latches: the two native-frame refusal modes
        # have different operator fixes, and the first firing must not
        # silence the other's diagnostic
        self._warned_no_native = False
        self._warned_tenant_native = False

    def start(self) -> None:
        # self-register observability like every other component
        metrics = getattr(self.service, "metrics", None)
        if metrics is not None:
            metrics.gauge("ingest_socket.frames", lambda: self.frames)  # alazlint: disable=ALZ010 -- racy int read is a metrics gauge; GIL-atomic, momentarily stale at worst
            metrics.gauge("ingest_socket.records", lambda: self.records)  # alazlint: disable=ALZ010 -- racy gauge read, see above
            metrics.gauge("ingest_socket.bad_frames", lambda: self.bad_frames)  # alazlint: disable=ALZ010 -- racy gauge read, see above
            metrics.gauge(
                "ingest_socket.unsupported_frames", lambda: self.unsupported_frames  # alazlint: disable=ALZ010 -- racy gauge read, see above
            )
            metrics.gauge(
                "ingest_socket.quarantined_frames", lambda: self.quarantined_frames  # alazlint: disable=ALZ010 -- racy gauge read, see above
            )
            metrics.gauge("ingest_socket.resyncs", lambda: self.resyncs)  # alazlint: disable=ALZ010 -- racy gauge read, see above
        t = threading.Thread(target=self._accept_loop, name="alaz-ingest-accept", daemon=True)
        t.start()
        with self._state_lock:
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._unix_path is not None:
            try:
                self._unix_path.unlink()
            except OSError:
                pass
        # drain in rounds: the accept loop may append one last connection
        # thread between our snapshot and its own _stop check — joining
        # the accept thread (in the first round) serializes that append,
        # so the next round's snapshot is guaranteed to see it
        while True:
            with self._state_lock:
                threads = list(self._threads)
                self._threads.clear()
            if not threads:
                break
            for t in threads:  # join OUTSIDE the lock: the accept loop takes it
                t.join(timeout=2)

    # -- internals -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError as exc:
                if self._stop.is_set():
                    return  # shutdown closed the listener
                # transient accept failure (EMFILE under connection
                # floods, ECONNABORTED): keep the listener alive
                log.warning(f"accept failed: {exc}")
                self._stop.wait(0.1)
                continue
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), name="alaz-ingest-conn", daemon=True
            )
            t.start()
            # track only live connections (per-batch clients would
            # otherwise grow this list without bound)
            with self._state_lock:
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)

    def _recv_exact(
        self, conn: socket.socket, n: int, carry: bytes = b""
    ) -> tuple[Optional[bytearray], bytes]:
        """Read exactly n bytes into one preallocated buffer (no copies:
        struct.unpack and np.frombuffer consume the bytearray directly),
        consuming ``carry`` — bytes already pulled off the socket by a
        resync scan — first. Returns (buf, remaining_carry); buf is None
        when the stream ended."""
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        if carry:
            take = min(n, len(carry))
            view[:take] = carry[:take]
            got = take
            carry = carry[take:]
        while got < n:
            try:
                k = conn.recv_into(view[got:], n - got)
            except socket.timeout:
                if self._stop.is_set():
                    return None, b""
                continue
            except OSError:
                return None, b""
            if k == 0:
                return None, b""
            got += k
        return buf, carry

    def _recv_some(self, conn: socket.socket, n: int) -> Optional[bytes]:
        """One bounded read (for the resync scanner); None on EOF/error."""
        while True:
            try:
                chunk = conn.recv(n)
            except socket.timeout:
                if self._stop.is_set():
                    return None
                continue
            except OSError:
                return None
            return chunk if chunk else None

    def _resync(
        self, conn: socket.socket, garbage: bytes, scanned_before: int
    ) -> tuple[Optional[bytes], int]:
        """Framing lost: scan the byte stream for the next frame magic.
        Returns ``(carry, scanned)`` where carry holds the bytes
        STARTING AT the magic (the next header read consumes them) and
        scanned is this scan's garbage byte count; carry is None when
        the stream ended first — or when the connection's cumulative
        garbage (``scanned_before`` + this scan) exceeds
        MAX_RESYNC_BYTES_PER_CONN: an agent that streams unframeable
        bytes without end gets dropped, not served a CPU spin. The scan
        starts at offset 1 of ``garbage`` — offset 0 is the header that
        just failed — and keeps a 3-byte tail between reads so a magic
        straddling a read boundary is found."""
        with self._state_lock:
            self.resyncs += 1
        budget = MAX_RESYNC_BYTES_PER_CONN - scanned_before
        scanned = 0
        buf = bytes(garbage)
        start = 1
        while True:
            idx = buf.find(_MAGIC_BYTES, start)
            if idx >= 0:
                scanned += idx
                with self._state_lock:
                    self.resync_bytes += idx
                return buf[idx:], scanned
            skipped = max(len(buf) - 3, 0)
            scanned += skipped
            with self._state_lock:
                self.resync_bytes += skipped
            if scanned >= budget:
                log.warning(
                    "resync budget exhausted "
                    f"({MAX_RESYNC_BYTES_PER_CONN} garbage bytes); "
                    "dropping connection"
                )
                return None, scanned
            tail = buf[-3:]
            chunk = self._recv_some(conn, 4096)
            if chunk is None:
                return None, scanned
            buf = tail + chunk
            start = 0

    @staticmethod
    def _rows_in(kind: int, length: int) -> Optional[int]:
        """Whole records the verified payload length can hold — the
        trusted row measure for ledger attribution (None for unknown
        kinds, whose record size we cannot know)."""
        if kind == KIND_NATIVE:
            from alaz_tpu.graph.native import NATIVE_RECORD_DTYPE

            return length // NATIVE_RECORD_DTYPE.itemsize
        dtype = _KIND_DTYPE.get(kind)
        return None if dtype is None else length // dtype.itemsize

    def _quarantine(self, count: Optional[int], why: str) -> None:
        """Account one rejected frame without dropping the connection."""
        with self._state_lock:
            self.bad_frames += 1
            self.quarantined_frames += 1
        if self._ledger is not None and count:
            self._ledger.add("quarantined", int(count), reason=why)
        log.warning(f"quarantined frame ({why}); stream continues")

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        carry = b""  # bytes a resync scan already pulled off the socket
        conn_garbage = 0  # cumulative resync-scanned bytes, this conn
        conn_quarantined = 0  # frames quarantined on this conn
        try:
            while not self._stop.is_set():
                if conn_quarantined > MAX_QUARANTINED_FRAMES_PER_CONN:
                    log.warning(
                        "quarantine budget exhausted "
                        f"({MAX_QUARANTINED_FRAMES_PER_CONN} frames); "
                        "dropping connection"
                    )
                    return
                header, carry = self._recv_exact(conn, FRAME_HEADER.size, carry)
                if header is None:
                    return
                magic, kind, tenant, count, length = FRAME_HEADER.unpack(header)
                if magic != MAGIC or length > MAX_FRAME_BYTES:
                    # header corruption: framing is lost — the count/
                    # length fields are untrustworthy, so no row count
                    # can be attributed; scan forward to the next magic
                    self._quarantine(None, "bad_header")
                    conn_quarantined += 1
                    carry, scanned = self._resync(
                        conn, bytes(header) + carry, conn_garbage
                    )
                    conn_garbage += scanned
                    if carry is None:
                        return
                    continue
                payload, carry = self._recv_exact(conn, length, carry)
                if payload is None:
                    return
                ok = self._dispatch(kind, count, payload, tenant)
                if ok is None:
                    # well-formed but unsupported here (native frame on a
                    # numpy-store service): config mismatch, not protocol
                    # corruption — keep the connection, drop the frame
                    with self._state_lock:
                        self.unsupported_frames += 1
                    continue
                if not ok:
                    # well-FRAMED but malformed payload (count/length
                    # mismatch, unknown kind): the boundary held, so the
                    # stream is still in sync — quarantine and continue.
                    # Rows attribute from the TRUSTED measure (payload
                    # bytes actually read / itemsize), never the count
                    # field — that field being wrong is why we're here,
                    # and a bit-flipped count must not poison the ledger
                    # with billions of phantom rows.
                    self._quarantine(
                        self._rows_in(kind, length), f"malformed_kind{kind}"
                    )
                    conn_quarantined += 1
                    continue
                with self._state_lock:
                    self.frames += 1
                    self.records += count
        finally:
            conn.close()

    def _dispatch(
        self, kind: int, count: int, payload: bytes | bytearray, tenant: int = 0
    ) -> bool | None:
        """True = accepted; False = malformed payload (quarantine the
        frame, keep the connection — framing held); None = well-formed
        but unsupported by this service's configuration."""
        if kind == KIND_NATIVE:
            from alaz_tpu.graph.native import NATIVE_RECORD_DTYPE

            if count * NATIVE_RECORD_DTYPE.itemsize != len(payload):
                return False
            if tenant:
                # the C++ window accumulator is a single-tenant plane: a
                # tenant-tagged native frame has no partition to land in
                # (config mismatch, not protocol corruption)
                if not self._warned_tenant_native:
                    self._warned_tenant_native = True
                    log.warning(
                        "agent sent a tenant-tagged native frame; the "
                        "native ring is single-tenant — use the event "
                        "kinds for multi-tenant fleets"
                    )
                return None
            if self._native_store is None:
                if not self._warned_no_native:
                    self._warned_no_native = True
                    log.warning(
                        "agent sent native frames but the service runs the "
                        "numpy store — start with use_native_ingest=True "
                        "(and build libalaz_ingest.so) to accept them"
                    )
                return None
            rows = np.frombuffer(payload, dtype=NATIVE_RECORD_DTYPE)
            # pre-attributed edges go straight into the native ring
            self._native_store.push_records(rows)
            return True
        dtype = _KIND_DTYPE.get(kind)
        if dtype is None or count * dtype.itemsize != len(payload):
            return False
        batch = np.frombuffer(payload, dtype=dtype)
        # tenant routing (ISSUE 14): tagged frames name their partition
        # explicitly; untagged (legacy) frames take the positional path
        # so pre-tenancy service duck-types keep working unchanged
        if kind == KIND_L7:
            if tenant:
                self.service.submit_l7(batch, tenant=tenant)
            else:
                self.service.submit_l7(batch)
        elif kind == KIND_TCP:
            if tenant:
                self.service.submit_tcp(batch, tenant=tenant)
            else:
                self.service.submit_tcp(batch)
        else:
            if tenant:
                self.service.submit_proc(batch, tenant=tenant)
            else:
                self.service.submit_proc(batch)
        return True


def send_batches(
    address: str | tuple, frames: list[tuple[int, np.ndarray]]
) -> None:
    """Client-side helper (tests / Python agents): connect, send, close."""
    if isinstance(address, str):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    else:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.connect(address)
    try:
        for kind, batch in frames:
            sock.sendall(pack_frame(kind, batch))
    finally:
        sock.close()
