"""Socket ingest plane for out-of-process agents — the process boundary
of SURVEY §2.3 P8 (the reference's kernel↔userspace perf-buffer seam,
re-drawn as agent↔service).

INTEGRATION.md's contract is "ship the event dtypes as raw bytes over
any transport"; this is that transport: a length-prefixed binary frame
protocol over a unix or TCP socket that a C/C++/Go agent can emit with
one writev per batch and zero serialization (numpy structured arrays are
fixed-layout).

Frame layout (little-endian, 16-byte header):

    u32 magic   = 0x414C5A31  ("ALZ1")
    u8  kind    = 1 l7 | 2 tcp | 3 proc | 4 native (AlzRecord rows)
    u8  _pad[3]
    u32 count   = number of records
    u32 length  = payload bytes (must equal count * itemsize)
    ...payload  = `count` packed records of the kind's dtype

kind 4 bypasses the aggregator: records are the 32-byte AlzRecord wire
format (graph/native.py) for pre-attributed edges pushed straight at the
windowed graph store — the "native fast path" of INTEGRATION.md over a
socket instead of in-process ctypes.

Malformed frames (bad magic, length mismatch, unknown kind) drop the
connection — the agent is the untrusted side. Backpressure follows the
service contract: submit_* drop-not-block, so a flooding agent loses
events rather than stalling the socket reader into TCP backpressure.
"""

from __future__ import annotations

import socket
import struct
import threading
from pathlib import Path
from typing import Optional

import numpy as np

from alaz_tpu.events.schema import (
    L7_EVENT_DTYPE,
    PROC_EVENT_DTYPE,
    TCP_EVENT_DTYPE,
)
from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.ingest_server")

MAGIC = 0x414C5A31
# Public: the 16-byte frame header IS the wire contract out-of-process
# agents compile against (agent_example.cc FrameHeader). alazspec pins
# its size/format in resources/specs/wire_layouts.json (ALZ021).
FRAME_HEADER = struct.Struct("<IB3xII")

KIND_L7 = 1
KIND_TCP = 2
KIND_PROC = 3
KIND_NATIVE = 4

_KIND_DTYPE = {
    KIND_L7: L7_EVENT_DTYPE,
    KIND_TCP: TCP_EVENT_DTYPE,
    KIND_PROC: PROC_EVENT_DTYPE,
}

MAX_FRAME_BYTES = 64 * 1024 * 1024  # one frame must fit in memory comfortably


def pack_frame(kind: int, batch: np.ndarray) -> bytes:
    """Client-side helper: one event batch → one wire frame."""
    payload = np.ascontiguousarray(batch).tobytes()
    return FRAME_HEADER.pack(MAGIC, kind, batch.shape[0], len(payload)) + payload


class IngestServer:
    """Accepts agent connections and feeds their frames into a Service.

    ``path`` starts a unix-domain listener; ``port`` a TCP one (use the
    loopback/TLS-terminating sidecar of your deployment for anything
    off-host — the reference's log streamer does the same)."""

    def __init__(
        self,
        service,
        path: str | Path | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.service = service
        self.frames = 0  # guarded-by: self._state_lock
        self.records = 0  # guarded-by: self._state_lock
        self.bad_frames = 0  # guarded-by: self._state_lock
        self.unsupported_frames = 0  # guarded-by: self._state_lock
        self._state_lock = threading.Lock()
        self._stop = threading.Event()
        # the accept loop rebinds/appends while stop() iterates — the
        # list must live under the state lock (alazlint ALZ010 finding,
        # fixed in ISSUE 2: a join missed mid-rebind leaked the thread)
        self._threads: list[threading.Thread] = []  # guarded-by: self._state_lock
        self._unix_path: Optional[Path] = None
        if path is not None:
            self._unix_path = Path(path)
            self.address: str | tuple = str(path)
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            if self._unix_path.exists():
                # A stale socket file from a previous run blocks bind —
                # but only unlink if nothing answers: silently stealing a
                # LIVE instance's listener would redirect its agents here.
                probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    probe.settimeout(0.5)
                    probe.connect(str(path))
                except (ConnectionRefusedError, FileNotFoundError):
                    # nothing accepting: stale file from a dead process.
                    # missing_ok: a concurrently-restarting sibling may
                    # have reclaimed it first — bind() then reports the
                    # conflict cleanly
                    self._unix_path.unlink(missing_ok=True)
                except (socket.timeout, BlockingIOError):
                    # a full backlog on a stalled-but-live listener shows
                    # as EAGAIN (BlockingIOError; AF_UNIX connect under
                    # settimeout is non-blocking) or as a timeout —
                    # ambiguity must favor NOT stealing
                    self._sock.close()
                    raise OSError(
                        f"ingest socket {path} did not answer a connect "
                        "probe but may be live (backlog full?); refusing "
                        "to steal it — remove the file manually if stale"
                    ) from None
                else:
                    self._sock.close()
                    raise OSError(
                        f"ingest socket {path} is in use by a live process; "
                        "refusing to steal its listener"
                    )
                finally:
                    probe.close()
            self._sock.bind(str(path))
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.bind((host, port))
            self.address = self._sock.getsockname()
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        # KIND_NATIVE needs the C++ ring (push_records); the numpy store
        # doesn't speak the wire record format
        store = getattr(service, "graph_store", None)
        self._native_store = store if hasattr(store, "push_records") else None
        self._warned_no_native = False

    def start(self) -> None:
        # self-register observability like every other component
        metrics = getattr(self.service, "metrics", None)
        if metrics is not None:
            metrics.gauge("ingest_socket.frames", lambda: self.frames)  # alazlint: disable=ALZ010 -- racy int read is a metrics gauge; GIL-atomic, momentarily stale at worst
            metrics.gauge("ingest_socket.records", lambda: self.records)  # alazlint: disable=ALZ010 -- racy gauge read, see above
            metrics.gauge("ingest_socket.bad_frames", lambda: self.bad_frames)  # alazlint: disable=ALZ010 -- racy gauge read, see above
            metrics.gauge(
                "ingest_socket.unsupported_frames", lambda: self.unsupported_frames  # alazlint: disable=ALZ010 -- racy gauge read, see above
            )
        t = threading.Thread(target=self._accept_loop, name="alaz-ingest-accept", daemon=True)
        t.start()
        with self._state_lock:
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._unix_path is not None:
            try:
                self._unix_path.unlink()
            except OSError:
                pass
        # drain in rounds: the accept loop may append one last connection
        # thread between our snapshot and its own _stop check — joining
        # the accept thread (in the first round) serializes that append,
        # so the next round's snapshot is guaranteed to see it
        while True:
            with self._state_lock:
                threads = list(self._threads)
                self._threads.clear()
            if not threads:
                break
            for t in threads:  # join OUTSIDE the lock: the accept loop takes it
                t.join(timeout=2)

    # -- internals -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError as exc:
                if self._stop.is_set():
                    return  # shutdown closed the listener
                # transient accept failure (EMFILE under connection
                # floods, ECONNABORTED): keep the listener alive
                log.warning(f"accept failed: {exc}")
                self._stop.wait(0.1)
                continue
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), name="alaz-ingest-conn", daemon=True
            )
            t.start()
            # track only live connections (per-batch clients would
            # otherwise grow this list without bound)
            with self._state_lock:
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)

    def _recv_exact(self, conn: socket.socket, n: int) -> Optional[bytearray]:
        """Read exactly n bytes into one preallocated buffer (no copies:
        struct.unpack and np.frombuffer consume the bytearray directly)."""
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            try:
                k = conn.recv_into(view[got:], n - got)
            except socket.timeout:
                if self._stop.is_set():
                    return None
                continue
            except OSError:
                return None
            if k == 0:
                return None
            got += k
        return buf

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        try:
            while not self._stop.is_set():
                header = self._recv_exact(conn, FRAME_HEADER.size)
                if header is None:
                    return
                magic, kind, count, length = FRAME_HEADER.unpack(header)
                if magic != MAGIC or length > MAX_FRAME_BYTES:
                    with self._state_lock:
                        self.bad_frames += 1
                    log.warning("bad frame header; dropping connection")
                    return
                payload = self._recv_exact(conn, length)
                if payload is None:
                    return
                ok = self._dispatch(kind, count, payload)
                if ok is None:
                    # well-formed but unsupported here (native frame on a
                    # numpy-store service): config mismatch, not protocol
                    # corruption — keep the connection, drop the frame
                    with self._state_lock:
                        self.unsupported_frames += 1
                    continue
                if not ok:
                    with self._state_lock:
                        self.bad_frames += 1
                    log.warning(f"malformed frame kind={kind}; dropping connection")
                    return
                with self._state_lock:
                    self.frames += 1
                    self.records += count
        finally:
            conn.close()

    def _dispatch(self, kind: int, count: int, payload: bytes | bytearray) -> bool | None:
        """True = accepted; False = malformed (drop connection); None =
        well-formed but unsupported by this service's configuration."""
        if kind == KIND_NATIVE:
            from alaz_tpu.graph.native import NATIVE_RECORD_DTYPE

            if count * NATIVE_RECORD_DTYPE.itemsize != len(payload):
                return False
            if self._native_store is None:
                if not self._warned_no_native:
                    self._warned_no_native = True
                    log.warning(
                        "agent sent native frames but the service runs the "
                        "numpy store — start with use_native_ingest=True "
                        "(and build libalaz_ingest.so) to accept them"
                    )
                return None
            rows = np.frombuffer(payload, dtype=NATIVE_RECORD_DTYPE)
            # pre-attributed edges go straight into the native ring
            self._native_store.push_records(rows)
            return True
        dtype = _KIND_DTYPE.get(kind)
        if dtype is None or count * dtype.itemsize != len(payload):
            return False
        batch = np.frombuffer(payload, dtype=dtype)
        if kind == KIND_L7:
            self.service.submit_l7(batch)
        elif kind == KIND_TCP:
            self.service.submit_tcp(batch)
        else:
            self.service.submit_proc(batch)
        return True


def send_batches(
    address: str | tuple, frames: list[tuple[int, np.ndarray]]
) -> None:
    """Client-side helper (tests / Python agents): connect, send, close."""
    if isinstance(address, str):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    else:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.connect(address)
    try:
        for kind, batch in frames:
            sock.sendall(pack_frame(kind, batch))
    finally:
        sock.close()
