"""Container log streaming — the logstreamer/ analog (G21).

The reference watches CRI log files with fsnotify, seeks preexisting files
to the end, and ships a metadata line + raw bytes over pooled TLS TCP
connections with a 1-byte liveness probe ('X' close marker, pool.go:24-45)
and a 10s container-poll reconcile (stream.go:324-430). The transport is a
pluggable connection factory: ``SocketConnection`` + ``dial`` below are
the production leg (TLS per stream.go:51-66 — but the CA comes from
env/config, not an embedded SaaS certificate), and tests use in-memory
sinks or a loopback TLS server. File watching is poll-based (inotify adds
a dependency for no behavioral difference at 10s reconcile granularity).
"""

from __future__ import annotations

import socket
import ssl
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.logstream")


class Connection:
    """Minimal conn surface: send(bytes), alive() probe, close()."""

    def send(self, data: bytes) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def alive(self) -> bool:
        return True

    def close(self) -> None:
        pass


class SocketConnection(Connection):
    """A pooled TCP/TLS connection. ``alive()`` is the pool.go:24-45
    probe: read one byte under a 1 ms deadline — a timeout means the
    peer simply has nothing to say (alive), EOF or an error means dead,
    and the byte ``X`` is the server's explicit close marker. Sends
    carry a deadline too: a peer that accepted the conn but stopped
    reading (zero TCP window) must not wedge the shipper thread —
    timeout surfaces as a send failure and the conn is retired."""

    def __init__(self, sock: socket.socket, send_timeout_s: float = 60.0):
        self._sock = sock
        self._send_timeout_s = send_timeout_s

    def send(self, data: bytes) -> None:
        self._sock.settimeout(self._send_timeout_s)
        try:
            self._sock.sendall(data)
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass

    def alive(self) -> bool:
        try:
            self._sock.settimeout(0.001)
            buf = self._sock.recv(1)
        except (TimeoutError, socket.timeout, ssl.SSLWantReadError, BlockingIOError):
            return True
        except OSError:
            return False
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass
        if not buf:  # EOF: peer closed
            return False
        if buf == b"X":  # explicit close marker
            return False
        return True  # unexpected data on a send-only conn: ignore

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _make_tls_context(ca_file: str | None) -> ssl.SSLContext:
    """CA resolution chain (the caCert.go embedded-bundle analog):
    an explicit ``ca_file`` pins a private CA; otherwise the system
    trust store, and when THAT is empty (slim containers routinely ship
    no /etc/ssl bundle — the situation the reference embeds its CA for)
    fall back to certifi's bundled roots if importable. A context with
    zero CAs would otherwise fail every handshake with a misleading
    verify error."""
    ctx = ssl.create_default_context(cafile=ca_file)
    if ca_file is None and not ctx.get_ca_certs():
        try:
            import certifi

            ctx.load_verify_locations(cafile=certifi.where())
        except Exception:  # noqa: BLE001 - no bundle anywhere: leave as-is
            pass
    return ctx


def dial(
    host: str,
    port: int,
    use_tls: bool = True,
    ca_file: str | None = None,
    server_name: str | None = None,
    timeout_s: float = 60.0,
) -> SocketConnection:
    """Production connection factory body (stream.go:81-105: 60 s dial
    timeout, TLS by default). ``ca_file`` pins a private CA; None falls
    back to the system trust store, then certifi's bundled roots
    (_make_tls_context — the analog of the reference's embedded SaaS CA,
    caCert.go, generalized to any backend)."""
    if use_tls:
        # build the context BEFORE dialing: a bad ca_file path must not
        # leak an established TCP fd per attempt
        ctx = _make_tls_context(ca_file)
    raw = socket.create_connection((host, port), timeout=timeout_s)
    if not use_tls:
        raw.settimeout(None)
        return SocketConnection(raw)
    try:
        wrapped = ctx.wrap_socket(raw, server_hostname=server_name or host)
    except BaseException:
        raw.close()
        raise
    wrapped.settimeout(None)
    return SocketConnection(wrapped)


def factory_from_env(env=None) -> Callable[[], Connection]:
    """Build the dial factory from the reference's env surface:
    LOG_BACKEND (host:port), LOG_BACKEND_TLS (default true),
    LOG_BACKEND_SERVER_NAME, plus LOG_BACKEND_CA_FILE for the CA pin
    (stream.go:51-66,76-124). All accept the ALAZ_TPU_ prefix like every
    other knob (config.lookup_env)."""
    from alaz_tpu.config import lookup_env, parse_bool

    backend = lookup_env("LOG_BACKEND", "", env) or ""
    if not backend or ":" not in backend:
        raise ValueError("LOG_BACKEND must be host:port")
    host, _, port_s = backend.rpartition(":")
    port = int(port_s)
    use_tls = parse_bool(lookup_env("LOG_BACKEND_TLS", None, env), True)
    ca_file = lookup_env("LOG_BACKEND_CA_FILE", None, env) or None
    server_name = lookup_env("LOG_BACKEND_SERVER_NAME", None, env) or None

    def factory() -> Connection:
        return dial(host, port, use_tls=use_tls, ca_file=ca_file, server_name=server_name)

    return factory


class ConnectionPool:
    """Channel-style pool with liveness checks (pool.go semantics): get()
    pops a pooled conn, discarding dead ones; put() returns it."""

    def __init__(self, factory: Callable[[], Connection], max_size: int = 4):
        self.factory = factory
        self.max_size = max_size
        self._pool: List[Connection] = []  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.created = 0  # guarded-by: self._lock
        self.discarded = 0  # guarded-by: self._lock

    def get(self) -> Connection:
        """Pop a live pooled conn, else dial. The ``alive()`` probe is a
        1 ms socket read — real I/O, so it runs OUTSIDE the lock (ALZ011
        in spirit: a stack of dead conns would otherwise stall every
        thread contending for the pool behind serial probe timeouts)."""
        while True:
            with self._lock:
                if not self._pool:
                    break
                conn = self._pool.pop()
            if conn.alive():
                return conn
            with self._lock:
                self.discarded += 1
            conn.close()
        with self._lock:
            self.created += 1
        return self.factory()

    def put(self, conn: Connection) -> None:
        # probe before taking the lock (same I/O-outside-lock rule);
        # worst case a racing put overfills by a probe's width and the
        # length re-check under the lock closes the extra conn
        if conn.alive():
            with self._lock:
                if len(self._pool) < self.max_size:
                    self._pool.append(conn)
                    return
        conn.close()

    def close(self) -> None:
        with self._lock:
            for c in self._pool:
                c.close()
            self._pool.clear()


@dataclass
class _Tail:
    path: Path
    pos: int
    meta: dict = field(default_factory=dict)


class LogStreamer:
    def __init__(
        self,
        pool: ConnectionPool,
        poll_interval_s: float = 10.0,
        read_interval_s: float = 0.5,
    ):
        self.pool = pool
        self.poll_interval_s = poll_interval_s
        self.read_interval_s = read_interval_s
        # watch/unwatch race the pump thread's snapshot
        self._tails: Dict[str, _Tail] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.bytes_sent = 0  # guarded-by: self._lock

    def watch(self, key: str, path: str | Path, metadata: dict | None = None, from_start: bool = False) -> None:
        """Start tailing a log file; preexisting content is skipped
        (seek-to-end, stream.go:324-352) unless from_start."""
        p = Path(path)
        pos = 0
        if not from_start:
            try:
                pos = p.stat().st_size
            except OSError:
                pos = 0
        with self._lock:
            self._tails[key] = _Tail(path=p, pos=pos, meta=metadata or {})

    def unwatch(self, key: str) -> None:
        with self._lock:
            self._tails.pop(key, None)

    def pump_once(self) -> int:
        """Read new bytes from every tail and ship them; returns bytes sent."""
        sent = 0
        with self._lock:
            tails = list(self._tails.items())
        for key, tail in tails:
            try:
                size = tail.path.stat().st_size
            except OSError:
                continue
            if size < tail.pos:  # rotation: start over
                tail.pos = 0
            if size == tail.pos:
                continue
            with open(tail.path, "rb") as f:
                f.seek(tail.pos)
                data = f.read(size - tail.pos)
                new_pos = f.tell()
            if not data:
                continue
            header = (
                "**AlazLogs_" + "_".join(str(v) for v in ([key] + list(tail.meta.values()))) + "\n"
            ).encode()
            conn = self.pool.get()
            try:
                conn.send(header + data)
            except Exception as exc:
                # don't advance: the bytes re-send next pump; the failing
                # conn is closed, not re-pooled
                log.warning(f"log send failed for {key}: {exc}")
                conn.close()
                continue
            tail.pos = new_pos
            sent += len(data)
            self.pool.put(conn)
        with self._lock:
            self.bytes_sent += sent
        return sent

    def start(self, service=None) -> None:
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.read_interval_s):
                try:
                    self.pump_once()
                except Exception as exc:
                    log.warning(f"log pump failed: {exc}")

        self._thread = threading.Thread(target=run, name="alaz-logstream", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self.pool.close()
