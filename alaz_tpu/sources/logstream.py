"""Container log streaming — the logstreamer/ analog (G21).

The reference watches CRI log files with fsnotify, seeks preexisting files
to the end, and ships a metadata line + raw bytes over pooled TLS TCP
connections with a 1-byte liveness probe ('X' close marker, pool.go:24-45)
and a 10s container-poll reconcile (stream.go:324-430). Here the transport
is a pluggable connection factory (sockets in production, in-memory sinks
in tests); file watching is poll-based (inotify adds a dependency for no
behavioral difference at 10s reconcile granularity).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.logstream")


class Connection:
    """Minimal conn surface: send(bytes), alive() probe, close()."""

    def send(self, data: bytes) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def alive(self) -> bool:
        return True

    def close(self) -> None:
        pass


class ConnectionPool:
    """Channel-style pool with liveness checks (pool.go semantics): get()
    pops a pooled conn, discarding dead ones; put() returns it."""

    def __init__(self, factory: Callable[[], Connection], max_size: int = 4):
        self.factory = factory
        self.max_size = max_size
        self._pool: List[Connection] = []
        self._lock = threading.Lock()
        self.created = 0
        self.discarded = 0

    def get(self) -> Connection:
        with self._lock:
            while self._pool:
                conn = self._pool.pop()
                if conn.alive():
                    return conn
                self.discarded += 1
                conn.close()
        self.created += 1
        return self.factory()

    def put(self, conn: Connection) -> None:
        with self._lock:
            if len(self._pool) < self.max_size and conn.alive():
                self._pool.append(conn)
                return
        conn.close()

    def close(self) -> None:
        with self._lock:
            for c in self._pool:
                c.close()
            self._pool.clear()


@dataclass
class _Tail:
    path: Path
    pos: int
    meta: dict = field(default_factory=dict)


class LogStreamer:
    def __init__(
        self,
        pool: ConnectionPool,
        poll_interval_s: float = 10.0,
        read_interval_s: float = 0.5,
    ):
        self.pool = pool
        self.poll_interval_s = poll_interval_s
        self.read_interval_s = read_interval_s
        self._tails: Dict[str, _Tail] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.bytes_sent = 0

    def watch(self, key: str, path: str | Path, metadata: dict | None = None, from_start: bool = False) -> None:
        """Start tailing a log file; preexisting content is skipped
        (seek-to-end, stream.go:324-352) unless from_start."""
        p = Path(path)
        pos = 0
        if not from_start:
            try:
                pos = p.stat().st_size
            except OSError:
                pos = 0
        with self._lock:
            self._tails[key] = _Tail(path=p, pos=pos, meta=metadata or {})

    def unwatch(self, key: str) -> None:
        with self._lock:
            self._tails.pop(key, None)

    def pump_once(self) -> int:
        """Read new bytes from every tail and ship them; returns bytes sent."""
        sent = 0
        with self._lock:
            tails = list(self._tails.items())
        for key, tail in tails:
            try:
                size = tail.path.stat().st_size
            except OSError:
                continue
            if size < tail.pos:  # rotation: start over
                tail.pos = 0
            if size == tail.pos:
                continue
            with open(tail.path, "rb") as f:
                f.seek(tail.pos)
                data = f.read(size - tail.pos)
                new_pos = f.tell()
            if not data:
                continue
            header = (
                "**AlazLogs_" + "_".join(str(v) for v in ([key] + list(tail.meta.values()))) + "\n"
            ).encode()
            conn = self.pool.get()
            try:
                conn.send(header + data)
            except Exception as exc:
                # don't advance: the bytes re-send next pump; the failing
                # conn is closed, not re-pooled
                log.warning(f"log send failed for {key}: {exc}")
                conn.close()
                continue
            tail.pos = new_pos
            sent += len(data)
            self.pool.put(conn)
        self.bytes_sent += sent
        return sent

    def start(self, service=None) -> None:
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.read_interval_s):
                try:
                    self.pump_once()
                except Exception as exc:
                    log.warning(f"log pump failed: {exc}")

        self._thread = threading.Thread(target=run, name="alaz-logstream", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self.pool.close()
