"""Event sources — the seam where events enter the framework.

The reference's sources are eBPF programs + k8s informers + CRI
(SURVEY §2.2 G2-G8, G19-G20). In the TPU-native build the kernel side is
replaced by pluggable sources behind one interface: replay/simulation for
tests and benchmarks (the configs in BASELINE.json), a k8s watch adapter
for live cluster metadata, a container index (the CRITool analog), a
TLS-attachment tracker, and a log streamer. A live eBPF agent feeds the
same surface by POSTing columnar event batches at a Service.
"""

from alaz_tpu.sources.base import EventSource
from alaz_tpu.sources.replay import ReplaySource
from alaz_tpu.sources.k8s_watch import K8sWatchSource, fan_out_containers
from alaz_tpu.sources.containers import ContainerIndex, ContainerInfo
from alaz_tpu.sources.tlsattach import TlsAttachTracker
from alaz_tpu.sources.logstream import LogStreamer, ConnectionPool

__all__ = [
    "EventSource",
    "ReplaySource",
    "K8sWatchSource",
    "fan_out_containers",
    "ContainerIndex",
    "ContainerInfo",
    "TlsAttachTracker",
    "LogStreamer",
    "ConnectionPool",
]
