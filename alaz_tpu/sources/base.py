"""The EventSource seam (the ebpf.EbpfCollector interface analog,
collector.go:40-64): a source owns its production loop and feeds a
Service's submit_* surface."""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class EventSource(Protocol):
    def start(self, service) -> None:  # Service or anything with submit_*
        ...

    def stop(self) -> None:
        ...
