"""CRI runtime client — the cri/cri.go analog (G20).

The reference talks CRI gRPC to the container runtime over candidate
unix sockets under /proc/1/root (containerd/crio/cri-dockerd,
cri.go:24-26), lists running containers, and resolves container → pids
via ContainerStatus's verbose info (main pid) plus a cgroup.procs walk
(cri.go:160-233). This is the from-scratch equivalent: a minimal
gRPC-over-HTTP/2 unary client built on the repo's own HTTP/2 framing and
HPACK codec (protocols/http2.py, protocols/hpack.py) and a hand-rolled
protobuf wire codec for the three CRI v1 RPCs used (Version,
ListContainers, ContainerStatus). Field numbers follow the public
kubernetes cri-api runtime/v1 api.proto.

``CriContainerLister`` adapts the client to the ContainerIndex lister
seam (sources/containers.py), so live nodes populate the index the same
way test fixtures do.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from pathlib import Path
from typing import Iterator, List, Optional

from alaz_tpu.logging import get_logger
from alaz_tpu.protocols import hpack, http2
from alaz_tpu.sources.containers import ContainerInfo, cgroup_pids

log = get_logger("alaz_tpu.cri")

# cri.go:24-26 candidate endpoints (host root via /proc/1/root)
DEFAULT_RUNTIME_SOCKETS = [
    "/proc/1/root/run/k3s/containerd/containerd.sock",
    "/proc/1/root/run/containerd/containerd.sock",
    "/proc/1/root/var/run/containerd/containerd.sock",
    "/proc/1/root/var/run/crio/crio.sock",
    "/proc/1/root/run/crio/crio.sock",
    "/proc/1/root/run/cri-dockerd.sock",
    "/proc/1/root/var/run/cri-dockerd.sock",
]

RUNTIME_SERVICE = "/runtime.v1.RuntimeService"

# kubelet-standard container labels (ContainerStatus/ListContainers)
LABEL_POD_UID = "io.kubernetes.pod.uid"
LABEL_POD_NAME = "io.kubernetes.pod.name"
LABEL_POD_NAMESPACE = "io.kubernetes.pod.namespace"
LABEL_CONTAINER_NAME = "io.kubernetes.container.name"

CONTAINER_STATE_RUNNING = 1  # pb.ContainerState_CONTAINER_RUNNING


# ---------------------------------------------------------------------------
# Minimal protobuf wire codec
# ---------------------------------------------------------------------------


def _uv(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def pb_varint(field: int, value: int) -> bytes:
    return _uv(field << 3 | 0) + _uv(value)


def pb_len(field: int, data: bytes) -> bytes:
    return _uv(field << 3 | 2) + _uv(len(data)) + data


def pb_str(field: int, s: str) -> bytes:
    return pb_len(field, s.encode("utf-8"))


def pb_fields(data: bytes) -> Iterator[tuple[int, int, int | bytes]]:
    """Walk protobuf wire fields → (field_no, wire_type, value). Varints
    yield ints; length-delimited yield bytes; fixed32/64 yield ints."""
    off = 0
    n = len(data)
    while off < n:
        key = 0
        shift = 0
        while True:
            if off >= n:
                return
            b = data[off]
            off += 1
            key |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field, wt = key >> 3, key & 0x7
        if wt == 0:
            val = 0
            shift = 0
            while True:
                if off >= n:
                    return
                b = data[off]
                off += 1
                val |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            yield field, wt, val
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                if off >= n:
                    return
                b = data[off]
                off += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            if off + ln > n:
                return
            yield field, wt, data[off : off + ln]
            off += ln
        elif wt == 1:
            if off + 8 > n:
                return
            yield field, wt, int.from_bytes(data[off : off + 8], "little")
            off += 8
        elif wt == 5:
            if off + 4 > n:
                return
            yield field, wt, int.from_bytes(data[off : off + 4], "little")
            off += 4
        else:  # groups (3/4): unsupported/legacy — stop rather than misparse
            return


def pb_map_entry(data: bytes) -> tuple[str, str]:
    """map<string,string> entry {key=1, value=2}."""
    k = v = ""
    for field, wt, val in pb_fields(data):
        if wt != 2:
            continue
        if field == 1:
            k = bytes(val).decode("utf-8", "replace")
        elif field == 2:
            v = bytes(val).decode("utf-8", "replace")
    return k, v


# ---------------------------------------------------------------------------
# gRPC unary client over a unix socket (HTTP/2 + HPACK from this repo)
# ---------------------------------------------------------------------------


class GrpcError(Exception):
    pass


class GrpcUnixClient:
    """Blocking unary-call gRPC client. One HTTP/2 connection, odd stream
    ids, HPACK via the repo codec; handles SETTINGS/PING/WINDOW_UPDATE
    bookkeeping and grpc-status trailers."""

    def __init__(self, socket_path: str, timeout_s: float = 10.0):
        self.socket_path = socket_path
        self.timeout_s = timeout_s
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout_s)
        self._sock.connect(socket_path)
        self._init_conn()

    def _init_conn(self) -> None:
        """Shared post-connect setup (TCP subclass reuses everything but
        the dial)."""
        self._enc = hpack.Encoder()
        self._dec = hpack.Decoder()
        self._buf = b""
        self._next_stream = 1
        self._lock = threading.Lock()
        self._sock.sendall(http2.MAGIC + http2.build_frame(http2.FRAME_SETTINGS, 0, 0))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _read_frame(self) -> http2.Frame:
        while True:
            if len(self._buf) >= 9:
                length = int.from_bytes(self._buf[:3], "big")
                if len(self._buf) >= 9 + length:
                    f = http2.parse_frame_header(self._buf)
                    self._buf = self._buf[9 + length :]
                    return f
            chunk = self._sock.recv(65536)
            if not chunk:
                raise GrpcError("connection closed by runtime")
            self._buf += chunk

    def call(self, path: str, request: bytes) -> bytes:
        """One unary RPC: returns the response message bytes (after the
        5-byte gRPC frame header); raises GrpcError on non-zero
        grpc-status."""
        with self._lock:
            stream_id = self._next_stream
            self._next_stream += 2
            headers = self._enc.encode(
                [
                    (":method", "POST"),
                    (":scheme", "http"),
                    (":path", path),
                    (":authority", "localhost"),
                    ("content-type", "application/grpc"),
                    ("te", "trailers"),
                ]
            )
            grpc_frame = b"\x00" + struct.pack("!I", len(request)) + request
            self._sock.sendall(  # alazlint: disable=ALZ011 -- the lock IS the RPC serializer: one in-flight unary call per h2 connection (shared _buf/_next_stream/hpack state); no thread does lock-free work
                http2.build_frame(
                    http2.FRAME_HEADERS, http2.FLAG_END_HEADERS, stream_id, headers
                )
                + http2.build_frame(
                    http2.FRAME_DATA, http2.FLAG_END_STREAM, stream_id, grpc_frame
                )
            )
            body = b""
            grpc_status: Optional[int] = None
            grpc_message = ""
            while True:
                f = self._read_frame()
                if f.type == http2.FRAME_SETTINGS:
                    if not f.flags & 0x1:  # ack theirs
                        self._sock.sendall(  # alazlint: disable=ALZ011 -- see above: whole-RPC lock is this client's serialization design
                            http2.build_frame(http2.FRAME_SETTINGS, 0x1, 0)
                        )
                    continue
                if f.type == http2.FRAME_PING:
                    if not f.flags & 0x1:
                        self._sock.sendall(  # alazlint: disable=ALZ011 -- see above: whole-RPC lock is this client's serialization design
                            http2.build_frame(http2.FRAME_PING, 0x1, 0, f.payload)
                        )
                    continue
                if f.type == http2.FRAME_GOAWAY:
                    raise GrpcError(f"GOAWAY from runtime: {f.payload[:64]!r}")
                if f.type == http2.FRAME_RST_STREAM and f.stream_id == stream_id:
                    raise GrpcError("stream reset by runtime")
                if f.stream_id != stream_id:
                    continue  # WINDOW_UPDATE etc. for other streams
                if f.type == http2.FRAME_HEADERS:
                    try:
                        for name, value in self._dec.decode(http2.headers_block(f)):
                            if name == "grpc-status":
                                grpc_status = int(value)
                            elif name == "grpc-message":
                                grpc_message = value
                    except hpack.HpackError as exc:
                        raise GrpcError(f"bad response headers: {exc}")
                elif f.type == http2.FRAME_DATA:
                    body += f.payload
                    if f.length:
                        # replenish flow-control windows (conn + stream)
                        inc = struct.pack("!I", f.length)
                        self._sock.sendall(  # alazlint: disable=ALZ011 -- see above: whole-RPC lock is this client's serialization design
                            http2.build_frame(http2.FRAME_WINDOW_UPDATE, 0, 0, inc)
                            + http2.build_frame(
                                http2.FRAME_WINDOW_UPDATE, 0, stream_id, inc
                            )
                        )
                if f.flags & http2.FLAG_END_STREAM:
                    break
            if grpc_status not in (None, 0):
                raise GrpcError(f"grpc-status {grpc_status}: {grpc_message}")
            if len(body) < 5:
                return b""
            if body[0] != 0:
                raise GrpcError("compressed gRPC responses unsupported")
            (msg_len,) = struct.unpack("!I", body[1:5])
            return body[5 : 5 + msg_len]


class GrpcTcpClient(GrpcUnixClient):
    """Same unary client over TCP (the libtpu runtime-metrics service
    listens on localhost:8431 — runtime/tpu_env.py)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.timeout_s = timeout_s
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._init_conn()


# ---------------------------------------------------------------------------
# CRI v1 typed surface
# ---------------------------------------------------------------------------


class CriContainer:
    __slots__ = ("id", "name", "pod_uid", "pod_name", "pod_namespace")

    def __init__(self, id: str, name: str, pod_uid: str, pod_name: str, pod_namespace: str):
        self.id = id
        self.name = name
        self.pod_uid = pod_uid
        self.pod_name = pod_name
        self.pod_namespace = pod_namespace


class CriClient:
    """Typed CRI v1 RuntimeService calls (the internalapi.RuntimeService
    subset the reference uses)."""

    def __init__(self, socket_path: str, timeout_s: float = 10.0):
        self.socket_path = socket_path
        self._grpc = GrpcUnixClient(socket_path, timeout_s)

    def close(self) -> None:
        self._grpc.close()

    def version(self) -> str:
        """VersionResponse.runtime_name/runtime_version — the probe RPC."""
        resp = self._grpc.call(f"{RUNTIME_SERVICE}/Version", pb_str(1, "v1"))
        name = ver = ""
        for field, wt, val in pb_fields(resp):
            if wt != 2:
                continue
            if field == 2:
                name = bytes(val).decode("utf-8", "replace")
            elif field == 3:
                ver = bytes(val).decode("utf-8", "replace")
        return f"{name} {ver}".strip()

    def list_containers(self) -> List[CriContainer]:
        """ListContainers(filter: state=RUNNING) (cri.go:100-120)."""
        # ListContainersRequest{filter=1{ContainerFilter: state=2{state=1}}}
        req = pb_len(1, pb_len(2, pb_varint(1, CONTAINER_STATE_RUNNING)))
        resp = self._grpc.call(f"{RUNTIME_SERVICE}/ListContainers", req)
        out: List[CriContainer] = []
        for field, wt, val in pb_fields(resp):
            if field != 1 or wt != 2:
                continue
            cid = cname = ""
            labels: dict[str, str] = {}
            for f2, w2, v2 in pb_fields(bytes(val)):
                if f2 == 1 and w2 == 2:
                    cid = bytes(v2).decode("utf-8", "replace")
                elif f2 == 3 and w2 == 2:  # ContainerMetadata{name=1}
                    for f3, w3, v3 in pb_fields(bytes(v2)):
                        if f3 == 1 and w3 == 2:
                            cname = bytes(v3).decode("utf-8", "replace")
                elif f2 == 8 and w2 == 2:  # labels map entry
                    k, v = pb_map_entry(bytes(v2))
                    labels[k] = v
            out.append(
                CriContainer(
                    id=cid,
                    name=labels.get(LABEL_CONTAINER_NAME, cname),
                    pod_uid=labels.get(LABEL_POD_UID, ""),
                    pod_name=labels.get(LABEL_POD_NAME, ""),
                    pod_namespace=labels.get(LABEL_POD_NAMESPACE, ""),
                )
            )
        return out

    def container_status(self, container_id: str) -> tuple[int, str, dict[str, str]]:
        """ContainerStatus(id, verbose=True) → (main pid, log_path, labels)
        (cri.go:160-190: pid comes from the verbose info JSON)."""
        req = pb_str(1, container_id) + pb_varint(2, 1)
        resp = self._grpc.call(f"{RUNTIME_SERVICE}/ContainerStatus", req)
        pid = 0
        log_path = ""
        labels: dict[str, str] = {}
        for field, wt, val in pb_fields(resp):
            if wt != 2:
                continue
            if field == 1:  # ContainerStatus
                for f2, w2, v2 in pb_fields(bytes(val)):
                    if f2 == 15 and w2 == 2:
                        log_path = bytes(v2).decode("utf-8", "replace")
                    elif f2 == 12 and w2 == 2:
                        k, v = pb_map_entry(bytes(v2))
                        labels[k] = v
            elif field == 2:  # info map
                k, v = pb_map_entry(bytes(val))
                if k == "info":
                    try:
                        pid = int(json.loads(v).get("pid", 0))
                    except (ValueError, TypeError):
                        pid = 0
        return pid, log_path, labels


def probe_runtime_socket(
    candidates: Optional[List[str]] = None, timeout_s: float = 2.0
) -> Optional[str]:
    """First candidate socket that answers the Version RPC (cri.go:39-63);
    CRI_RUNTIME_ENDPOINT env takes priority."""
    paths = list(candidates) if candidates is not None else list(DEFAULT_RUNTIME_SOCKETS)
    env = os.environ.get("CRI_RUNTIME_ENDPOINT", "")
    if env:
        paths.insert(0, env.removeprefix("unix://"))
    for path in paths:
        try:
            if not Path(path).exists():
                continue
        except OSError:  # /proc/1/root may deny traversal in containers
            continue
        try:
            client = CriClient(path, timeout_s=timeout_s)
            try:
                ver = client.version()
            finally:
                client.close()
            log.info(f"connected to CRI at {path} ({ver})")
            return path
        except (OSError, GrpcError) as exc:
            log.debug(f"CRI probe {path} failed: {exc}")
    return None


class CriContainerLister:
    """ContainerIndex lister over a CRI socket: container → main pid via
    verbose status, then the pid's cgroup walked for the full pid set
    (cri.go:192-233), log path prefixed with the host root."""

    def __init__(
        self,
        socket_path: str,
        host_root: str = "/proc/1/root",
        timeout_s: float = 10.0,
    ):
        self.socket_path = socket_path
        self.host_root = host_root.rstrip("/")
        self.timeout_s = timeout_s
        self._client: Optional[CriClient] = None

    def _get_client(self) -> CriClient:
        if self._client is None:
            self._client = CriClient(self.socket_path, self.timeout_s)
        return self._client

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None

    def _pids_for(self, main_pid: int) -> set[int]:
        """Main pid → every pid in its cgroup (v2: /sys/fs/cgroup<path>;
        v1: memory controller), read through the host root."""
        if main_pid <= 0:
            return set()
        pids: set[int] = set()
        cgroup_file = Path(self.host_root) / "proc" / str(main_pid) / "cgroup"
        try:
            lines = cgroup_file.read_text().splitlines()
        except OSError:
            return pids
        for line in lines:
            parts = line.split(":", 2)
            if len(parts) != 3:
                continue
            hierarchy, controllers, cpath = parts
            if hierarchy == "0":  # cgroup v2
                procs = f"{self.host_root}/sys/fs/cgroup{cpath}/cgroup.procs"
            elif "memory" in controllers.split(","):
                procs = f"{self.host_root}/sys/fs/cgroup/memory{cpath}/cgroup.procs"
            else:
                continue
            pids |= cgroup_pids(procs)
        if not pids:
            pids = {main_pid}
        return pids

    def __call__(self) -> List[ContainerInfo]:
        client = self._get_client()
        try:
            containers = client.list_containers()
        except (OSError, GrpcError):
            self.close()  # reconnect next sync
            raise
        out: List[ContainerInfo] = []
        for c in containers:
            try:
                pid, log_path, _labels = client.container_status(c.id)
            except (OSError, GrpcError) as exc:
                log.warning(f"container status {c.id[:12]} failed: {exc}")
                continue
            out.append(
                ContainerInfo(
                    container_id=c.id,
                    name=c.name,
                    namespace=c.pod_namespace or "default",
                    pod_uid=c.pod_uid,
                    pids=self._pids_for(pid),
                    log_path=f"{self.host_root}{log_path}" if log_path else "",
                )
            )
        return out
