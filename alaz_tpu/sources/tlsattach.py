"""TLS attachment tracking — the uprobe-attach queue analog
(collector.go:276-317 + ebpf/ssllib.go).

The reference dedups attach requests per pid (tlsPidMap), discovers the
process's TLS library from /proc/<pid>/maps (libssl flavors incl. the
"(deleted)" edge case, ssllib.go:9-80), and dispatches version-specific
uprobes. In this build the "attachment" marks a pid whose decrypted
traffic a capture adapter should label tls=1; the discovery/dedup
contract is kept so a live agent can drive real attach hooks through
``on_attach``.
"""

from __future__ import annotations

import re
import threading
from pathlib import Path
from typing import Callable, Dict, Optional

from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.tls")

# libssl flavors, matching the reference's regex set (ssllib.go:9-40):
# libssl.so[.version], libssl3.so, and deleted-but-mapped libraries
_LIBSSL_RE = re.compile(
    r"(?P<path>/[^\s]*libssl(?P<flavor>3)?\.so(?:\.(?P<version>[0-9][0-9.]*))?)"
    r"(?P<deleted>\s+\(deleted\))?"
)


def find_ssl_lib(maps_text: str) -> Optional[dict]:
    """Parse /proc/<pid>/maps content → {path, version, deleted} or None."""
    best = None
    for line in maps_text.splitlines():
        m = _LIBSSL_RE.search(line)
        if not m:
            continue
        version = m.group("version") or ("3" if m.group("flavor") else "")
        cand = {
            "path": m.group("path"),
            "version": version,
            "deleted": bool(m.group("deleted")),
        }
        if best is None or (best["deleted"] and not cand["deleted"]):
            best = cand
    return best


def ssl_version_family(version: str) -> str:
    """semver-dispatch buckets (collector.go:577-657): 1.0.2 / 1.1.1 / 3.x."""
    if version.startswith("3"):
        return "v3"
    if version.startswith("1.1"):
        return "v1.1.1"
    if version.startswith("1.0"):
        return "v1.0.2"
    return "unknown"


class TlsAttachTracker:
    def __init__(
        self,
        on_attach: Optional[Callable[[int, dict], None]] = None,
        proc_root: str | Path = "/proc",
    ):
        self.on_attach = on_attach
        self.proc_root = Path(proc_root)
        self.attached: Dict[int, dict] = {}
        # pids whose exe was already checked and is NOT a Go TLS user: a
        # process's binary never gains buildinfo later, so the negative
        # result is permanent (unlike libssl, which can dlopen late) —
        # without this every retried signal re-reads a up-to-200MB exe
        self._not_go: set[int] = set()
        self._lock = threading.Lock()

    def signal(self, pid: int) -> bool:
        """Request attachment for a pid; dedup per pid (tlsPidMap).
        Returns True if this call performed an attachment. A failed
        discovery (no libssl mapped *yet* — dlopen, slow start) is NOT
        cached, so later signals retry."""
        with self._lock:
            if pid in self.attached:
                return False
            self.attached[pid] = {}  # reserve before the slow path
        info = self._discover(pid)
        with self._lock:
            if pid not in self.attached:
                return False  # concurrently detached: don't resurrect
            if not info:
                del self.attached[pid]  # retry on the next signal
                return False
            self.attached[pid] = info
        if self.on_attach is not None:
            self.on_attach(pid, info)
        return True

    def detach(self, pid: int) -> None:
        with self._lock:
            self.attached.pop(pid, None)
            self._not_go.discard(pid)  # a reused pid is a different exe

    def _discover(self, pid: int) -> dict:
        maps_path = self.proc_root / str(pid) / "maps"
        try:
            text = maps_path.read_text()
        except OSError:
            return {}
        lib = find_ssl_lib(text)
        if lib is not None:
            lib["family"] = ssl_version_family(lib["version"])
            return lib
        # no libssl mapped: maybe a Go binary using crypto/tls — resolve
        # the uprobe plan from the executable's ELF (collector.go:319-516)
        with self._lock:
            if pid in self._not_go:
                return {}
        from alaz_tpu.sources.gotls import discover_go_tls

        exe = self.proc_root / str(pid) / "exe"
        plan = discover_go_tls(exe) if exe.exists() else None
        if plan is None:
            with self._lock:
                self._not_go.add(pid)
            return {}
        return {
            "path": str(exe),
            "version": plan.go_version,
            "deleted": False,
            "family": "go-tls",
            "plan": plan,
        }
