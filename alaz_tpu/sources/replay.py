"""Replay source: simulator or recorded trace → Service queues.

Supports flat-out replay (throughput benchmarking) and real-time pacing
(the reference simulator's rate.Limiter behavior,
main_benchmark_test.go:561-617).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from alaz_tpu.config import SimulationConfig
from alaz_tpu.events.intern import Interner
from alaz_tpu.replay.simulator import Simulator


class ReplaySource:
    def __init__(
        self,
        config: SimulationConfig,
        interner: Interner,
        realtime: bool = False,
    ):
        self.sim = Simulator(config, interner=interner)
        self.realtime = realtime
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.emitted = 0

    def start(self, service) -> None:
        self._stop.clear()

        def run() -> None:
            for msg in self.sim.setup():
                service.submit_k8s(msg)
            service.submit_tcp(self.sim.tcp_events())
            rate = self.sim.cfg.edge_rate * self.sim.cfg.edge_count  # events/s
            t0 = time.monotonic()
            for batch in self.sim.iter_l7_batches():
                if self._stop.is_set():
                    return
                if self.realtime and rate > 0:
                    # pace so `emitted` tracks wall time × rate
                    target = self.emitted / rate
                    ahead = target - (time.monotonic() - t0)
                    if ahead > 0:
                        time.sleep(ahead)
                service.submit_l7(batch)
                self.emitted += batch.shape[0]

        self._thread = threading.Thread(target=run, name="alaz-replay", daemon=True)
        self._thread.start()

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        self.join(2)
        self._thread = None
