"""CLI — the main.go analog, env-driven with subcommands.

  python -m alaz_tpu serve   [--config testconfig/config1.json] [--ckpt DIR]
  python -m alaz_tpu replay  [--config ...]        # data-plane acceptance
  python -m alaz_tpu train   [--config ...] [--model graphsage] [--ckpt DIR]
  python -m alaz_tpu bench                          # headline JSON line
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _honor_jax_platforms() -> None:
    """Make JAX_PLATFORMS effective even when a site plugin pre-imported
    jax (plugin environments register their backend at interpreter start,
    so the env var alone is too late — force it via config)."""
    plat = os.environ.get("JAX_PLATFORMS", "")
    if not plat or plat == "axon":
        return
    try:
        import jax

        jax.config.update("jax_platforms", plat)
    except Exception:
        pass


def _sim_config(path: str | None):
    from alaz_tpu.config import SimulationConfig

    if path:
        return SimulationConfig.from_json(path)
    return SimulationConfig(test_duration_s=10.0, pod_count=100, service_count=50, edge_count=40, edge_rate=1000)


def cmd_replay(args) -> int:
    from alaz_tpu.replay.simulator import run_replay

    res = run_replay(_sim_config(args.config))
    print(
        json.dumps(
            {
                "generated": res.generated,
                "persisted": res.persisted,
                "processed_ratio": round(res.processed_ratio, 4),
                "events_per_s": round(res.events_per_s),
                "passed": res.passed,
            }
        )
    )
    return 0 if res.passed else 1


def cmd_train(args) -> int:
    import numpy as np

    from alaz_tpu.config import ModelConfig
    from alaz_tpu.replay.scenario import run_anomaly_scenario
    from alaz_tpu.train import checkpoint
    from alaz_tpu.train.metrics import auroc
    from alaz_tpu.train.trainstep import (
        make_score_fn,
        score_batch,
        train_on_batches,
        train_tgn_unrolled,
    )

    sim_cfg = _sim_config(args.config)
    cfg = ModelConfig(model=args.model)
    data = run_anomaly_scenario(sim_cfg, n_windows=args.windows, fault_fraction=0.15, seed=args.seed)
    if args.model == "tgn":
        # temporal model: unroll windows with memory threaded so the
        # GRU/memory params train. One update per epoch covers the whole
        # sequence, so the step count is scaled and reported.
        tgn_steps = max(args.epochs * 3, 20)
        print(
            f"tgn: {tgn_steps} unrolled update steps over "
            f"{len(data.train)} windows (from --epochs {args.epochs})",
            file=sys.stderr,
        )
        state, losses = train_tgn_unrolled(cfg, data.train, epochs=tgn_steps)
    else:
        state, losses = train_on_batches(cfg, data.train, epochs=args.epochs)
    scores, labels, masks = [], [], []
    if args.model == "tgn":
        # stream chronologically with memory threaded (service semantics)
        import jax
        import jax.numpy as jnp

        from alaz_tpu.models import tgn

        mem = tgn.init_memory(
            cfg, max(cfg.tgn_max_nodes, max(b.n_pad for b in data.all_batches))
        )
        jstep = jax.jit(lambda p, g, m: tgn.step(p, g, m, cfg))
        eval_ids = {id(b) for b in data.eval}
        for b in data.all_batches:
            g = {k: jnp.asarray(v) for k, v in b.device_arrays().items()}
            out, mem = jstep(state.params, g, mem)
            if id(b) in eval_ids:
                scores.append(np.asarray(out["edge_logits"]))
                labels.append(b.edge_label)
                masks.append(b.edge_mask)
    else:
        fn = make_score_fn(cfg)
        for b in data.eval:
            out = score_batch(cfg, state.params, b, fn)
            scores.append(out["edge_logits"])
            labels.append(b.edge_label)
            masks.append(b.edge_mask)
    a = auroc(np.concatenate(scores), np.concatenate(labels), np.concatenate(masks))
    # per-failure-class breakdown (README taxonomy: latency_spike /
    # error_burst / zombie) — a blended number can hide a blind class
    from alaz_tpu.replay.faults import FAULT_KINDS
    from alaz_tpu.train.metrics import auroc_by_kind

    kind_arrays = [getattr(b, "edge_fault_kind", None) for b in data.eval]
    by_kind = {}
    if all(k is not None for k in kind_arrays) and kind_arrays:
        by_kind = {
            k: (round(v, 4) if v == v else None)  # NaN → null
            for k, v in auroc_by_kind(
                np.concatenate(scores),
                np.concatenate(kind_arrays),
                FAULT_KINDS,
                np.concatenate(masks),
            ).items()
        }
    if args.ckpt:
        checkpoint.save(args.ckpt, step=state.step, params=state.params)
    print(json.dumps({
        "model": args.model, "auroc": round(float(a), 4),
        "auroc_by_kind": by_kind,
        "loss_final": round(losses[-1], 4), "steps": state.step,
    }))
    return 0 if a >= 0.9 else 1


def cmd_serve(args) -> int:
    from alaz_tpu.config import RuntimeConfig
    from alaz_tpu.events.intern import Interner
    from alaz_tpu.runtime.debug_http import DebugServer
    from alaz_tpu.runtime.health import HealthChecker
    from alaz_tpu.runtime.service import Service
    from alaz_tpu.sources.replay import ReplaySource

    cfg = RuntimeConfig.from_env()
    if not args.config and not cfg.local_pids:
        # Live serve without LOCAL_PIDS: the procfs backfill and zombie
        # reaper stay off. They are explicit opt-in (LOCAL_PIDS=1, with
        # PROC_ROOT=/host/proc when containerized) because probing agent
        # pids against the wrong pid namespace tears down live join state.
        print(
            "serve: LOCAL_PIDS not set — procfs backfill and zombie "
            "reaper disabled (set LOCAL_PIDS=1 and PROC_ROOT if agent "
            "pids are resolvable on this node)",
            file=sys.stderr,
        )
    interner = Interner()
    params = None
    if args.ckpt:
        from alaz_tpu.train import checkpoint

        _, state = checkpoint.restore(args.ckpt)
        params = state["params"]

    export_backend = None
    if cfg.backend.host:
        from alaz_tpu.datastore.backend import BatchingBackend, http_transport

        export_backend = BatchingBackend(
            http_transport(cfg.backend.host), interner, cfg.backend
        )
        export_backend.start()

    svc = Service(
        config=cfg, interner=interner, model_state=params, export_backend=export_backend
    )
    # pre-existing connections join immediately on restart (reference
    # rebuilds state from /proc; replay configs have no live procfs)
    containers = None
    if cfg.local_pids:
        svc.aggregator.backfill_from_proc()
        # live container index over CRI when a runtime socket answers
        # (cri.go:39-73); replay mode has no runtime
        from alaz_tpu.sources.containers import ContainerIndex
        from alaz_tpu.sources.cri import CriContainerLister, probe_runtime_socket

        cri_sock = probe_runtime_socket()
        if cri_sock:
            containers = ContainerIndex(lister=CriContainerLister(cri_sock))
            containers.start(svc)
    svc.start()
    # live k8s informers (k8s/informer.go:67-157): in-cluster discovery
    # by default, K8S_API_SERVER override for tests/out-of-cluster.
    # Replay configs carry their own k8s messages, so live serve only.
    k8s_src = None
    if cfg.k8s_enabled and not args.config:
        from alaz_tpu.sources.k8s_watch import K8sWatchSource

        k8s_src = K8sWatchSource(
            exclude_namespaces=[
                ns.strip() for ns in cfg.exclude_namespaces.split(",") if ns.strip()
            ],
            api_server=cfg.k8s_api_server or None,
            token_file=cfg.k8s_token_file or None,
            ca_file=cfg.k8s_ca_file or None,
        )
        k8s_src.start(svc)
        if k8s_src.live:
            print("k8s informers watching", file=sys.stderr)
    ingest_srv = None
    if args.ingest_socket:
        from alaz_tpu.sources.ingest_server import IngestServer

        ingest_srv = IngestServer(svc, path=args.ingest_socket)
        ingest_srv.start()  # self-registers its ingest_socket.* gauges
        print(f"ingest socket at {args.ingest_socket}", file=sys.stderr)
    debug = DebugServer(svc, port=args.debug_port)
    debug.start()
    hc = None
    if cfg.backend.host:
        from alaz_tpu.datastore.backend import http_transport

        hc = HealthChecker(
            http_transport(cfg.backend.host),
            on_stop=svc.pause,
            on_resume=svc.resume,
            metrics_snapshot=svc.metrics.snapshot,
        )
        hc.start()
    src = None
    if args.config:
        src = ReplaySource(_sim_config(args.config), interner, realtime=not args.flat_out)
        src.start(svc)
    print(f"serving; debug http on :{debug.port}", file=sys.stderr)
    try:
        if src is not None:
            src.join()
            svc.drain(30)
            svc.flush_windows()
            svc.drain(30)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if src:
            src.stop()
        if ingest_srv is not None:
            ingest_srv.stop()
        if containers is not None:
            containers.stop()
        if k8s_src is not None:
            k8s_src.stop()
        if hc:
            hc.stop()
        debug.stop()
        svc.stop()
        if export_backend is not None:
            export_backend.stop()
    return 0


def cmd_bench(args) -> int:
    import bench

    bench.main()
    return 0


def main(argv=None) -> int:
    _honor_jax_platforms()
    p = argparse.ArgumentParser(prog="alaz_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("replay", help="data-plane acceptance replay")
    pr.add_argument("--config", default=None)
    pr.set_defaults(fn=cmd_replay)

    pt = sub.add_parser("train", help="train + AUROC-gate an anomaly scorer")
    pt.add_argument("--config", default=None)
    pt.add_argument("--model", default="graphsage")
    pt.add_argument("--epochs", type=int, default=20)
    pt.add_argument("--windows", type=int, default=10)
    pt.add_argument("--seed", type=int, default=0)
    pt.add_argument("--ckpt", default=None)
    pt.set_defaults(fn=cmd_train)

    ps = sub.add_parser("serve", help="run the streaming scoring service")
    ps.add_argument("--config", default=None, help="replay traffic config (omit for external ingest)")
    ps.add_argument("--ckpt", default=None)
    ps.add_argument("--debug-port", type=int, default=8181)
    ps.add_argument("--flat-out", action="store_true")
    ps.add_argument(
        "--ingest-socket", default=os.environ.get("INGEST_SOCKET", ""),
        help="unix socket for out-of-process agents (frame protocol in "
        "sources/ingest_server.py)",
    )
    ps.set_defaults(fn=cmd_serve)

    pb = sub.add_parser("bench", help="headline benchmark")
    pb.set_defaults(fn=cmd_bench)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
