"""CLI — the main.go analog, env-driven with subcommands.

  python -m alaz_tpu serve   [--config testconfig/config1.json] [--ckpt DIR]
  python -m alaz_tpu replay  [--config ...]        # data-plane acceptance
  python -m alaz_tpu train   [--config ...] [--model graphsage] [--ckpt DIR]
  python -m alaz_tpu bench                          # headline JSON line
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time


def _honor_jax_platforms() -> None:
    """Make JAX_PLATFORMS effective even when a site plugin pre-imported
    jax (plugin environments register their backend at interpreter start,
    so the env var alone is too late — force it via config)."""
    plat = os.environ.get("JAX_PLATFORMS", "")
    if not plat or plat == "axon":
        return
    try:
        import jax

        jax.config.update("jax_platforms", plat)
    except Exception:
        pass


def _sim_config(path: str | None):
    from alaz_tpu.config import SimulationConfig

    if path:
        return SimulationConfig.from_json(path)
    return SimulationConfig(test_duration_s=10.0, pod_count=100, service_count=50, edge_count=40, edge_rate=1000)


def cmd_replay(args) -> int:
    from alaz_tpu.replay.simulator import run_replay

    res = run_replay(_sim_config(args.config))
    print(
        json.dumps(
            {
                "generated": res.generated,
                "persisted": res.persisted,
                "processed_ratio": round(res.processed_ratio, 4),
                "events_per_s": round(res.events_per_s),
                "passed": res.passed,
            }
        )
    )
    return 0 if res.passed else 1


def _stream_tgn_eval(cfg, params, data, collect_next: bool = False):
    """Stream ALL windows chronologically with memory threaded (service
    semantics), collecting (scores, labels, masks, kinds[, labels_next])
    for the eval windows."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from alaz_tpu.models import tgn

    if not data.eval:
        # possible at --windows 1: n_train = max(1, ...) can consume
        # every batch — fail here with the fix, not 4-way-unpack later
        raise RuntimeError(
            "no eval windows were produced (every window landed in the "
            "train split); increase --windows"
        )
    mem = tgn.init_memory(
        cfg, max(cfg.tgn_max_nodes, max(b.n_pad for b in data.all_batches))
    )
    jstep = tgn.make_step_fn(cfg)  # cached per config — no per-run retrace
    eval_ids = {id(b) for b in data.eval}
    out_rows = []
    for b in data.all_batches:
        g = {k: jnp.asarray(v) for k, v in b.device_arrays().items()}
        out, mem = jstep(params, g, mem)
        if id(b) in eval_ids:
            row = [
                np.asarray(out["edge_logits"]),
                b.edge_label,
                b.edge_mask,
                getattr(b, "edge_fault_kind", None),
            ]
            if collect_next:
                row.append(b.edge_label_next)
            out_rows.append(row)
    return [list(col) for col in zip(*out_rows)]


def _train_eval_one(model: str, sim_cfg, windows: int, epochs: int, seed: int,
                    ckpt: str | None = None) -> dict:
    """Train one model on the anomaly scenario and evaluate AUROC
    (blended + per-fault-class). The shared core of ``train`` and
    ``eval``."""
    import numpy as np

    from alaz_tpu.config import ModelConfig
    from alaz_tpu.replay.faults import FAULT_KINDS
    from alaz_tpu.replay.scenario import run_anomaly_scenario
    from alaz_tpu.train import checkpoint
    from alaz_tpu.train.metrics import auroc, auroc_by_kind
    from alaz_tpu.train.trainstep import (
        make_score_fn,
        score_batch,
        train_on_batches,
        train_tgn_unrolled,
    )

    # from_env so knobs like EDGE_FEAT_ZNORM=0 shape the TRAINED model
    # too — otherwise no checkpoint matching a znorm-off serve config
    # could ever be produced and the contract gate's "set the env to
    # match" advice would be unsatisfiable
    cfg = dataclasses.replace(ModelConfig.from_env(), model=model)
    data = run_anomaly_scenario(sim_cfg, n_windows=windows, fault_fraction=0.15, seed=seed)
    if not data.eval:
        # possible at --windows 1: n_train = max(1, ...) can consume
        # every batch; fail with the fix, not an opaque concatenate error
        raise RuntimeError(
            "no eval windows were produced (every window landed in the "
            "train split); increase --windows"
        )
    if model == "tgn":
        # temporal model: unroll windows with memory threaded so the
        # GRU/memory params train. One update per epoch covers the whole
        # train sequence — epochs * len(train) unrolled updates puts TGN
        # at STEP PARITY with the per-window models, which take one step
        # per (epoch, window) (r03 trained it half as long and it
        # showed).
        tgn_steps = max(epochs * len(data.train), 20)
        print(
            f"tgn: {tgn_steps} unrolled update steps over "
            f"{len(data.train)} windows (from --epochs {epochs})",
            file=sys.stderr,
        )
        state, losses = train_tgn_unrolled(cfg, data.train, epochs=tgn_steps, seed=seed)
        scores, labels, masks, kind_arrays = _stream_tgn_eval(cfg, state.params, data)
    else:
        state, losses = train_on_batches(cfg, data.train, epochs=epochs, seed=seed)
        scores, labels, masks, kind_arrays = [], [], [], []
        fn = make_score_fn(cfg)
        for b in data.eval:
            out = score_batch(cfg, state.params, b, fn)
            scores.append(out["edge_logits"])
            labels.append(b.edge_label)
            masks.append(b.edge_mask)
            kind_arrays.append(getattr(b, "edge_fault_kind", None))
    a = auroc(np.concatenate(scores), np.concatenate(labels), np.concatenate(masks))
    # per-failure-class breakdown (README taxonomy: latency_spike /
    # error_burst / zombie) — a blended number can hide a blind class
    by_kind = {}
    if kind_arrays and all(k is not None for k in kind_arrays):
        by_kind = {
            k: (round(v, 4) if v == v else None)  # NaN → null
            for k, v in auroc_by_kind(
                np.concatenate(scores),
                np.concatenate(kind_arrays),
                FAULT_KINDS,
                np.concatenate(masks),
            ).items()
        }
    if ckpt:
        checkpoint.save(
            ckpt, step=state.step, params=state.params,
            contract=checkpoint.feature_contract(cfg),
        )
    return {
        "model": model, "auroc": round(float(a), 4),
        "auroc_by_kind": by_kind,
        "loss_final": round(losses[-1], 4), "steps": state.step,
    }


def _tgn_forecast_eval(
    sim_cfg, windows: int, epochs: int, seed: int, train_seeds: int = 3
) -> dict:
    """BASELINE config 4's forecasting leg: train TGN on
    ``edge_label_next`` over ``train_seeds`` ramp scenarios (DIFFERENT
    fault draws — one draw lets the model memorize WHICH edges ramp
    instead of learning the drift signature) and evaluate on a fully
    held-out draw. Reported against next-window labels: blended AUROC,
    the persistence baseline (score = current label) the temporal model
    must beat for the memory to mean anything, and onset AUROC — only
    currently-clean edges, the calls persistence cannot make."""
    import numpy as np
    import optax

    from alaz_tpu.config import ModelConfig
    from alaz_tpu.replay.scenario import run_forecast_scenario
    from alaz_tpu.train.metrics import auroc
    from alaz_tpu.train.trainstep import train_tgn_unrolled

    cfg = dataclasses.replace(ModelConfig.from_env(), model="tgn")
    train_seqs = [
        run_forecast_scenario(
            sim_cfg, n_windows=windows, fault_fraction=0.15, seed=seed + s
        ).all_batches
        for s in range(train_seeds)
    ]
    heldout = run_forecast_scenario(
        sim_cfg, n_windows=windows, fault_fraction=0.15, seed=seed + 1000
    )
    tgn_steps = max(epochs * 5, 20)
    state, losses = train_tgn_unrolled(
        cfg,
        train_seqs,
        epochs=tgn_steps,
        lr=optax.cosine_decay_schedule(3e-3, tgn_steps),
        seed=seed,
        label_attr="edge_label_next",
    )
    scores, cur_labels, masks, _kinds, labels_next = _stream_tgn_eval(
        cfg, state.params, heldout, collect_next=True
    )
    s = np.concatenate(scores)
    c = np.concatenate(cur_labels)
    nx = np.concatenate(labels_next)
    m = np.concatenate(masks).astype(bool)
    f_auroc = auroc(s, nx, m)
    p_auroc = auroc(c, nx, m)
    onset = m & (c == 0)
    o_auroc = auroc(s[onset], nx[onset], np.ones(int(onset.sum())))

    def _r(v: float):
        # auroc is NaN when a slice has no positives or no negatives
        # (possible for the onset slice at tiny --forecast-windows);
        # bare NaN is invalid JSON — emit null like auroc_by_kind does
        return round(float(v), 4) if v == v else None

    return {
        "model": "tgn", "task": "forecast_next_window",
        "forecast_auroc": _r(f_auroc),
        "onset_auroc": _r(o_auroc),
        "persistence_auroc": _r(p_auroc),
        "n_onset_positives": int(nx[onset].sum()),
        "loss_final": round(losses[-1], 4), "steps": state.step,
    }


def cmd_train(args) -> int:
    sim_cfg = _sim_config(args.config)
    res = _train_eval_one(
        args.model, sim_cfg, args.windows, args.epochs, args.seed, args.ckpt
    )
    print(json.dumps(res))
    return 0 if res["auroc"] >= 0.9 else 1


def cmd_eval(args) -> int:
    """One-command reproduction of the full quality matrix (EVAL_rN.json):
    four models on the 10k-pod mixed config + the TGN forecast leg on the
    temporal config, seeds/windows/epochs pinned by the defaults."""
    from alaz_tpu.config import SimulationConfig

    det_cfg = SimulationConfig.from_json(args.config)
    results = [
        _train_eval_one(m, det_cfg, args.windows, args.epochs, args.seed)
        for m in args.models.split(",")
    ]
    for r in results:
        print(json.dumps(r), file=sys.stderr)
    fc_cfg = SimulationConfig.from_json(args.forecast_config)
    forecast = _tgn_forecast_eval(
        fc_cfg, args.forecast_windows, args.epochs, args.seed
    )
    print(json.dumps(forecast), file=sys.stderr)
    out = {
        "description": (
            "Quality gate at FULL scale: python -m alaz_tpu eval "
            f"--config {args.config} --windows {args.windows} --epochs "
            f"{args.epochs} --seed {args.seed} (deterministic: seeds/"
            "windows/epochs pinned by defaults). Detection: >=0.9 AUROC "
            "north star (BASELINE.json). Forecast: TGN on "
            f"{args.forecast_config} ramped latency faults, AUROC vs "
            "next-window labels."
        ),
        "config": args.config,
        "n_windows": args.windows,
        "epochs": args.epochs,
        "seed": args.seed,
        "fault_fraction": 0.15,
        "results": results,
        "forecast": forecast,
    }
    payload = json.dumps(out, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    print(payload)
    gate = all(r["auroc"] >= 0.9 for r in results)
    return 0 if gate else 1


def cmd_serve(args) -> int:
    from alaz_tpu.config import RuntimeConfig
    from alaz_tpu.events.intern import Interner
    from alaz_tpu.runtime.debug_http import DebugServer
    from alaz_tpu.runtime.health import HealthChecker
    from alaz_tpu.runtime.service import Service
    from alaz_tpu.sources.replay import ReplaySource

    cfg = RuntimeConfig.from_env()
    if not args.config and not cfg.local_pids:
        # Live serve without LOCAL_PIDS: the procfs backfill and zombie
        # reaper stay off. They are explicit opt-in (LOCAL_PIDS=1, with
        # PROC_ROOT=/host/proc when containerized) because probing agent
        # pids against the wrong pid namespace tears down live join state.
        print(
            "serve: LOCAL_PIDS not set — procfs backfill and zombie "
            "reaper disabled (set LOCAL_PIDS=1 and PROC_ROOT if agent "
            "pids are resolvable on this node)",
            file=sys.stderr,
        )
    interner = Interner()
    params = None
    if args.ckpt:
        from alaz_tpu.train import checkpoint

        _, state = checkpoint.restore(
            args.ckpt,
            expect_contract=checkpoint.feature_contract(cfg.model),
        )
        params = state["params"]

    export_backend = None
    if cfg.backend.host:
        from alaz_tpu.datastore.backend import BatchingBackend, http_transport

        export_backend = BatchingBackend(
            http_transport(cfg.backend.host), interner, cfg.backend
        )
        export_backend.start()

    svc = Service(
        config=cfg, interner=interner, model_state=params, export_backend=export_backend
    )
    # pre-existing connections join immediately on restart (reference
    # rebuilds state from /proc; replay configs have no live procfs)
    containers = None
    if cfg.local_pids:
        svc.aggregator.backfill_from_proc()
        # live container index over CRI when a runtime socket answers
        # (cri.go:39-73); replay mode has no runtime
        from alaz_tpu.sources.containers import ContainerIndex
        from alaz_tpu.sources.cri import CriContainerLister, probe_runtime_socket

        cri_sock = probe_runtime_socket()
        if cri_sock:
            containers = ContainerIndex(lister=CriContainerLister(cri_sock))
            containers.start(svc)
    svc.start()
    # live k8s informers (k8s/informer.go:67-157): in-cluster discovery
    # by default, K8S_API_SERVER override for tests/out-of-cluster.
    # Replay configs carry their own k8s messages, so live serve only.
    k8s_src = None
    if cfg.k8s_enabled and not args.config:
        from alaz_tpu.sources.k8s_watch import K8sWatchSource

        k8s_src = K8sWatchSource(
            exclude_namespaces=[
                ns.strip() for ns in cfg.exclude_namespaces.split(",") if ns.strip()
            ],
            api_server=cfg.k8s_api_server or None,
            token_file=cfg.k8s_token_file or None,
            ca_file=cfg.k8s_ca_file or None,
        )
        k8s_src.start(svc)
        if k8s_src.live:
            print("k8s informers watching", file=sys.stderr)
    ingest_srv = None
    if args.ingest_socket:
        from alaz_tpu.sources.ingest_server import IngestServer

        ingest_srv = IngestServer(svc, path=args.ingest_socket)
        ingest_srv.start()  # self-registers its ingest_socket.* gauges
        print(f"ingest socket at {args.ingest_socket}", file=sys.stderr)
    debug = DebugServer(svc, port=args.debug_port)
    debug.start()
    hc = None
    if cfg.backend.host:
        from alaz_tpu.datastore.backend import http_transport

        hc = HealthChecker(
            http_transport(cfg.backend.host),
            on_stop=svc.pause,
            on_resume=svc.resume,
            metrics_snapshot=svc.metrics.snapshot,
        )
        hc.start()
    src = None
    if args.config:
        src = ReplaySource(_sim_config(args.config), interner, realtime=not args.flat_out)
        src.start(svc)
    print(f"serving; debug http on :{debug.port}", file=sys.stderr)
    try:
        if src is not None:
            # bounded-join poll, not one unbounded join (alazflow
            # ALZ042): same wait-for-replay semantics, but the serve
            # thread re-enters Python once a second — signals stay
            # deliverable and a wedged replay thread is observable
            # instead of absorbing the process forever
            while src.alive():
                src.join(1.0)
            svc.drain(30)
            svc.flush_windows()
            svc.drain(30)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if src:
            src.stop()
        if ingest_srv is not None:
            ingest_srv.stop()
        if containers is not None:
            containers.stop()
        if k8s_src is not None:
            k8s_src.stop()
        if hc:
            hc.stop()
        debug.stop()
        svc.stop()
        if export_backend is not None:
            export_backend.stop()
    return 0


def cmd_bench(args) -> int:
    import bench

    bench.main()
    return 0


def main(argv=None) -> int:
    _honor_jax_platforms()
    p = argparse.ArgumentParser(prog="alaz_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("replay", help="data-plane acceptance replay")
    pr.add_argument("--config", default=None)
    pr.set_defaults(fn=cmd_replay)

    pt = sub.add_parser("train", help="train + AUROC-gate an anomaly scorer")
    pt.add_argument("--config", default=None)
    pt.add_argument("--model", default="graphsage")
    pt.add_argument("--epochs", type=int, default=20)
    pt.add_argument("--windows", type=int, default=10)
    pt.add_argument("--seed", type=int, default=0)
    pt.add_argument("--ckpt", default=None)
    pt.set_defaults(fn=cmd_train)

    ps = sub.add_parser("serve", help="run the streaming scoring service")
    ps.add_argument("--config", default=None, help="replay traffic config (omit for external ingest)")
    ps.add_argument("--ckpt", default=None)
    ps.add_argument("--debug-port", type=int, default=8181)
    ps.add_argument("--flat-out", action="store_true")
    ps.add_argument(
        "--ingest-socket", default=os.environ.get("INGEST_SOCKET", ""),
        help="unix socket for out-of-process agents (frame protocol in "
        "sources/ingest_server.py)",
    )
    ps.set_defaults(fn=cmd_serve)

    pe = sub.add_parser(
        "eval",
        help="regenerate the full quality matrix (EVAL_rN.json) deterministically",
    )
    pe.add_argument("--config", default="testconfig/config3_10k_mixed.json")
    pe.add_argument("--forecast-config", default="testconfig/config4_temporal.json")
    pe.add_argument("--models", default="graphsage,gat,experts,tgn")
    pe.add_argument("--epochs", type=int, default=30)
    pe.add_argument("--windows", type=int, default=10)
    pe.add_argument("--forecast-windows", type=int, default=20)
    pe.add_argument("--seed", type=int, default=0)
    pe.add_argument("--out", default=None)
    pe.set_defaults(fn=cmd_eval)

    pb = sub.add_parser("bench", help="headline benchmark")
    pb.set_defaults(fn=cmd_bench)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
