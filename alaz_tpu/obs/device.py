"""Device-side observability plane (ISSUE 11): per-bucket score
telemetry, staging decomposition, occupancy/padding accounting, and the
always-on compile event plane.

PR 9 gave the host plane per-stage attribution, but the two
device-facing span stages stayed single opaque numbers: ``stage`` mixed
host array prep with the host→device transfer, and ``score`` summed
every shape bucket into one histogram. Every ROADMAP north-star item
(Pallas fused-aggregation kernels, mixed-precision scoring arms,
multi-tenant continuous batching) needs its win attributed *per kernel,
per bucket* before it can be claimed — FeatGraph and the GNN-aggregation
architecture studies (PAPERS.md) both show accelerator aggregation cost
is dominated by layout/occupancy effects invisible without that
resolution. This module opens the box:

- :class:`DeviceTelemetry` — the staging/scoring accountant:

  * ``stage`` decomposes into **arena** (host array prep / arena fill)
    vs **transfer** (``jnp.asarray`` dispatch) histograms
    (``latency.stage_arena_s`` / ``latency.stage_transfer_s``) plus a
    cumulative ``device.transfer_bytes`` ledger;
  * ``score`` feeds a **per-bucket** labeled histogram
    (``latency.score_s.<bucket>``, bucket = ``n<N_pad>xe<E_pad>``) next
    to the span plane's aggregate, so a regression in ONE bucket can't
    hide inside the fleet p99;
  * **occupancy accounting at staging time**: every staged window
    observes ``rows / bucket capacity`` into ``device.occupancy.<bucket>``
    and accumulates real vs padded edge slots — the
    ``device.pad_waste_pct`` gauge is the TPU-native efficiency number
    the bucketed-CSR/Pallas work will be judged by.

- :class:`CompileEventPlane` — sanitize's ``CompileWatcher`` promoted
  from test fixture to production hookup: XLA compile events (traced-fn
  name, shape bucket, duration) count into ``compile.*`` metrics and
  land in the :class:`~alaz_tpu.obs.recorder.FlightRecorder`, so a
  steady-state retrace shows up on ``/metrics`` and in crash dumps
  instead of only under ``make sanitize``. The scorer thread tags the
  current bucket through a thread-local context, which is exact because
  XLA compiles synchronously on the dispatching thread.

Cost discipline (the ≤2 % bench bound): every observation here is per
**window × dispatch**, never per row or per edge; per-bucket series are
created lazily on first observation and registered *sparse* — a bucket
with zero observations is omitted from ``/metrics`` and the snapshot,
never rendered as an empty series.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Optional

from alaz_tpu.obs.histogram import Histogram


def bucket_key(batch) -> str:
    """The bucket label a GraphBatch scores under: its padded (node,
    edge) capacities — exactly the pair that keys the jit cache, so one
    label == one compiled program shape. Delegates to
    ``GraphBatch.bucket_key`` (graph/snapshot.py), the one definition."""
    return batch.bucket_key


def pad_waste_pct_from(real_slots: int, pad_slots: int) -> float:
    """THE pad-waste definition: percentage of edge slots that are pad,
    not data; 0.0 on empty (vacuously efficient, never NaN). Every
    surface that publishes pad waste — the device gauge, the builder's
    host-side counters (bench), the chaos harness — computes through
    here, so the formula cannot drift between `/stats` and the bench."""
    total = real_slots + pad_slots
    return 100.0 * pad_slots / total if total else 0.0


def batch_pad_waste_pct(batches) -> float:
    """Padding waste over a set of emitted batches (the chaos-harness
    form of :func:`pad_waste_pct_from`)."""
    real = sum(int(b.n_edges) for b in batches)
    slots = sum(int(b.e_pad) for b in batches)
    return pad_waste_pct_from(real, slots - real)


def blocked_pad_waste_pct_from(real_slots: int, block_slots: int) -> float:
    """The blocked layout's waste through the SAME definition: under
    ``EDGE_LAYOUT=blocked`` the aggregation paths process the per-block
    tile slots (graph/snapshot.blocked_edge_slots_from), not the bucket
    rung, so waste = the tile slots that aren't real edges. Feeding
    :func:`pad_waste_pct_from` keeps the two layouts' numbers directly
    comparable — same formula, different slot denominator (ISSUE 20)."""
    return pad_waste_pct_from(real_slots, max(block_slots - real_slots, 0))


# occupancy is a LINEAR 0..1 ratio, not a latency: on the default 2x
# geometric ladder a 55% and a 100% window land in the same bucket and
# interpolation can report >100%. A 5%-step linear ladder gives
# percentiles within 5 points and caps at exactly 1.0 — occupancy
# histograms merge only with like-bounded peers (the Histogram merge
# contract), which per-bucket series never need to violate.
OCCUPANCY_BOUNDS = tuple(round(0.05 * i, 2) for i in range(1, 21))


class _BucketStats:
    """Per-bucket telemetry cell: score latency + occupancy histograms
    and exact staged/scored counters. ``block_fill_hist`` is created
    lazily on the first BLOCKED window (the batch shipped extents) —
    a COO-only deployment never registers the series (sparse, absent
    not zero)."""

    __slots__ = ("score_hist", "occupancy_hist", "block_fill_hist",
                 "staged", "scored")

    def __init__(self, score_hist: Histogram, occupancy_hist: Histogram):
        self.score_hist = score_hist
        self.occupancy_hist = occupancy_hist
        self.block_fill_hist: Optional[Histogram] = None
        self.staged = 0  # windows staged (occupancy observations)
        self.scored = 0  # windows scored (score_hist observations)


class DeviceTelemetry:
    """Staging/scoring accountant for one scorer (see module docstring).

    ``metrics``: a runtime ``Metrics`` registry — per-bucket histograms
    register sparse as ``latency.score_s.<bucket>`` /
    ``device.occupancy.<bucket>``; the decomposition histograms and the
    ``device.*`` gauges register eagerly. ``metrics=None`` (tests,
    host-only pipelines) keeps private histograms.

    ``enabled=False`` short-circuits every observe at the first branch —
    the DEVICE_TRACE_ENABLED kill switch.
    """

    def __init__(self, metrics=None, recorder=None, enabled: bool = True):
        self.enabled = enabled
        self.metrics = metrics
        self.recorder = recorder
        self._lock = threading.Lock()
        self._buckets: Dict[str, _BucketStats] = {}  # guarded-by: self._lock
        # exact cumulative accounting (edge slots, not rows-of-bytes):
        # pad_waste_pct = padded / (staged + padded) — the gauges read
        # these, so a scrape mid-window is off by at most one window
        self.staged_windows = 0  # guarded-by: self._lock
        self.staged_edges = 0  # real (masked-in) edge slots  # guarded-by: self._lock
        self.padded_edge_slots = 0  # pad tail slots  # guarded-by: self._lock
        self.transfer_bytes = 0  # host→device bytes dispatched  # guarded-by: self._lock
        # blocked-layout twin ledger (ISSUE 20): real edges vs the tile
        # slots the blocked reduce touches, accumulated only for windows
        # that shipped extents — a COO deployment leaves both at 0
        self.blocked_staged_edges = 0  # guarded-by: self._lock
        self.blocked_edge_slots = 0  # tile slots  # guarded-by: self._lock
        if metrics is not None and enabled:
            self.arena_hist = metrics.histogram("latency.stage_arena_s")
            self.transfer_hist = metrics.histogram("latency.stage_transfer_s")
            metrics.gauge("device.transfer_bytes", lambda: self.transfer_bytes)
            metrics.gauge("device.staged_windows", lambda: self.staged_windows)
            metrics.gauge("device.staged_edges", lambda: self.staged_edges)
            metrics.gauge(
                "device.padded_edge_slots", lambda: self.padded_edge_slots
            )
            metrics.gauge("device.pad_waste_pct", lambda: self.pad_waste_pct)
            metrics.gauge("device.block_fill_pct", lambda: self.block_fill_pct)
        else:
            # disabled (or registry-less): keep private histograms and
            # register NOTHING — a killed plane must be absent from the
            # scrape, not render pad_waste_pct=0 as if collection were
            # live and clean (the same absent-not-zero discipline the
            # sparse per-bucket series follow)
            self.arena_hist = Histogram("latency.stage_arena_s")
            self.transfer_hist = Histogram("latency.stage_transfer_s")
            if not enabled:
                self.metrics = None  # per-bucket registration off too

    # -- bucket registry -----------------------------------------------------

    def _bucket(self, key: str) -> _BucketStats:
        # LOCK ORDER: the histogram registration below takes the Metrics
        # registry lock, and the registry holds ITS lock while reading
        # the device.pad_waste_pct gauge — so registration must happen
        # with the device lock RELEASED or a /metrics scrape racing a
        # first-bucket staging deadlocks ABBA (caught in review;
        # regression-tested). Double-checked: racers both build, one
        # wins the insert; the histograms are registry-shared either way.
        with self._lock:
            b = self._buckets.get(key)
        if b is not None:
            return b
        if self.metrics is not None:
            # sparse: a registered-but-never-observed bucket is OMITTED
            # from snapshot/exposition (the ISSUE 11 empty-series
            # discipline, next to the PR 9 gauge-error rule), never
            # rendered as a zero/NaN series
            nb = _BucketStats(
                self.metrics.histogram(f"latency.score_s.{key}", sparse=True),
                self.metrics.histogram(
                    f"device.occupancy.{key}", sparse=True,
                    bounds=OCCUPANCY_BOUNDS,
                ),
            )
        else:
            nb = _BucketStats(
                Histogram(f"latency.score_s.{key}"),
                Histogram(f"device.occupancy.{key}", bounds=OCCUPANCY_BOUNDS),
            )
        with self._lock:
            return self._buckets.setdefault(key, nb)

    def _block_fill_hist(self, key: str, b: _BucketStats) -> Histogram:
        # same ABBA discipline as _bucket: the registry registration
        # runs with the device lock RELEASED; double-checked, racers
        # both build and one wins (the histogram is registry-shared
        # under a Metrics registry either way)
        with self._lock:
            h = b.block_fill_hist
        if h is not None:
            return h
        if self.metrics is not None:
            nh = self.metrics.histogram(
                f"device.block_fill.{key}", sparse=True,
                bounds=OCCUPANCY_BOUNDS,
            )
        else:
            nh = Histogram(f"device.block_fill.{key}", bounds=OCCUPANCY_BOUNDS)
        with self._lock:
            if b.block_fill_hist is None:
                b.block_fill_hist = nh
            return b.block_fill_hist

    # -- staging side --------------------------------------------------------

    def observe_staged(self, batch) -> None:
        """One window entered the staging path: occupancy (rows vs
        bucket capacity) + the pad-waste ledger. Called once per REAL
        window — group-padding duplicates are not re-counted. A window
        that shipped blocked extents additionally feeds the block-fill
        ledger and its per-bucket histogram (the blocked layout's
        occupancy twin)."""
        if not self.enabled:
            return
        key = bucket_key(batch)
        e_pad = int(batch.e_pad)
        n_edges = int(batch.n_edges)
        b = self._bucket(key)
        b.occupancy_hist.observe(float(batch.edge_occupancy))
        block_slots = 0
        if getattr(batch, "edge_block_starts", None) is not None:
            block_slots = int(batch.blocked_edge_slots)
            if block_slots > 0:
                # fill ratio over the slots the blocked reduce touches
                # (real <= slots by construction, so the 0..1 linear
                # occupancy ladder applies unchanged)
                self._block_fill_hist(key, b).observe(n_edges / block_slots)
        with self._lock:
            b.staged += 1
            self.staged_windows += 1
            self.staged_edges += n_edges
            self.padded_edge_slots += e_pad - n_edges
            if block_slots > 0:
                self.blocked_staged_edges += n_edges
                self.blocked_edge_slots += block_slots

    def observe_transfer(
        self, n_bytes: int, arena_s: float, transfer_s: float
    ) -> None:
        """One staging dispatch (a serial window or a whole vmapped
        group): the arena/prep vs host→device split, plus bytes."""
        if not self.enabled:
            return
        self.arena_hist.observe(arena_s)
        self.transfer_hist.observe(transfer_s)
        with self._lock:
            self.transfer_bytes += int(n_bytes)

    # -- scoring side --------------------------------------------------------

    def observe_score(self, batch, dur_s: float) -> None:
        """One window's device step time, attributed to its bucket.
        Group members share the group dispatch duration — the same
        critical-path semantics the span plane's ``score`` stage uses."""
        if not self.enabled:
            return
        b = self._bucket(bucket_key(batch))
        b.score_hist.observe(dur_s)
        with self._lock:
            b.scored += 1

    # -- read side -----------------------------------------------------------

    @property
    def pad_waste_pct(self) -> float:
        # LOCKLESS read: this property backs a registered gauge, and the
        # Metrics registry reads gauges while holding its own lock —
        # taking the device lock here closes the ABBA cycle _bucket()
        # avoids (see the lock-order note there). Two GIL-atomic int
        # reads; a scrape racing a staging is off by at most one window.
        staged = self.staged_edges  # alazlint: disable=ALZ010 -- intentionally racy gauge read; locking here would ABBA-deadlock against the Metrics registry lock (see _bucket)
        padded = self.padded_edge_slots  # alazlint: disable=ALZ010 -- same intentionally racy read as the line above
        return pad_waste_pct_from(staged, padded)

    @property
    def block_fill_pct(self) -> float:
        # LOCKLESS for the same ABBA reason as pad_waste_pct (this backs
        # a registered gauge); 0.0 until the first blocked window —
        # mirrors pad_waste_pct's never-NaN empty reading
        real = self.blocked_staged_edges  # alazlint: disable=ALZ010 -- intentionally racy gauge read, see pad_waste_pct
        slots = self.blocked_edge_slots  # alazlint: disable=ALZ010 -- same intentionally racy read as the line above
        if not slots:
            return 0.0
        return 100.0 - blocked_pad_waste_pct_from(real, slots)

    def snapshot(self) -> dict:
        """The ``/stats`` per-bucket breakdown (next to the span plane's
        ``stage_latency``): occupancy + score percentiles per bucket,
        the stage decomposition, and the pad-waste ledger."""
        with self._lock:
            buckets = dict(self._buckets)
            out = {
                "pad_waste_pct": round(
                    pad_waste_pct_from(
                        self.staged_edges, self.padded_edge_slots
                    ),
                    3,
                ),
                "staged_windows": self.staged_windows,
                "staged_edges": self.staged_edges,
                "padded_edge_slots": self.padded_edge_slots,
                "transfer_bytes": self.transfer_bytes,
            }
            if self.blocked_edge_slots:
                # blocked ledger rides /stats only once a blocked window
                # staged (the sparse absent-not-zero discipline)
                out["block_fill_pct"] = round(
                    100.0
                    - blocked_pad_waste_pct_from(
                        self.blocked_staged_edges, self.blocked_edge_slots
                    ),
                    3,
                )
                out["blocked_edge_slots"] = self.blocked_edge_slots
        # histogram walks take the stripe locks — outside the plane lock
        arena, transfer = self.arena_hist.snapshot(), self.transfer_hist.snapshot()
        out["stage_split_ms"] = {
            "arena": {
                "count": arena["count"],
                "p50_ms": round(arena["p50"] * 1e3, 4),
                "p99_ms": round(arena["p99"] * 1e3, 4),
            },
            "transfer": {
                "count": transfer["count"],
                "p50_ms": round(transfer["p50"] * 1e3, 4),
                "p99_ms": round(transfer["p99"] * 1e3, 4),
            },
        }
        per_bucket = {}
        for key, b in sorted(buckets.items()):
            score = b.score_hist.snapshot()
            occ = b.occupancy_hist.snapshot()
            per_bucket[key] = {
                "staged": b.staged,
                "scored": b.scored,
                "score_p50_ms": round(score["p50"] * 1e3, 4),
                "score_p95_ms": round(score["p95"] * 1e3, 4),
                "score_p99_ms": round(score["p99"] * 1e3, 4),
                "occupancy_p50_pct": round(occ["p50"] * 100.0, 2),
                "occupancy_p99_pct": round(occ["p99"] * 100.0, 2),
            }
            if b.block_fill_hist is not None:
                fill = b.block_fill_hist.snapshot()
                per_bucket[key]["block_fill_p50_pct"] = round(
                    fill["p50"] * 100.0, 2
                )
        out["buckets"] = per_bucket
        return out


def _metric_safe(name: str) -> str:
    """Traced-fn names can carry non-identifier characters
    (``<lambda>``); the closed metric registry and the Prometheus
    exposition both need a clean token."""
    import re

    return re.sub(r"[^0-9A-Za-z_]", "_", name)


class CompileEventPlane:
    """Always-on XLA compile capture (see module docstring).

    ``start()`` opens a :class:`~alaz_tpu.sanitize.retrace.CompileWatcher`
    for the plane's lifetime (the service owns one per process-resident
    scorer; jax's ``log_compiles`` flag is saved/restored on stop). Each
    "Compiling <fn>" event counts into ``compile.events`` and
    ``compile.<fn>``; each "Finished XLA compilation" event carries the
    duration and lands in the flight recorder with the bucket the scorer
    thread declared via :meth:`bucket`.

    The steady-state contract this makes operational: after warmup,
    ``compile.<entry point>`` counters FREEZE — any later increment on
    a dashboard is a serving-path retrace (shape outside the bucket set,
    fresh jit wrapper, Python-type flip; see alazsan/ALZ006), caught in
    production instead of only under ``make sanitize``.
    """

    def __init__(self, metrics=None, recorder=None, enabled: bool = True):
        self.enabled = enabled
        self.metrics = metrics
        self.recorder = recorder
        self.events = 0  # "Compiling" count — guarded-by: self._lock
        self.by_fn: Dict[str, int] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._tls = threading.local()  # current bucket, scorer-thread-set
        self._watcher = None
        if metrics is not None:
            self._c_events = metrics.counter("compile.events")
        else:
            self._c_events = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "CompileEventPlane":
        if not self.enabled or self._watcher is not None:
            return self
        from alaz_tpu.sanitize.retrace import CompileWatcher

        self._watcher = CompileWatcher(on_event=self._on_event)
        self._watcher.__enter__()
        return self

    def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.__exit__()
            self._watcher = None

    # -- bucket attribution --------------------------------------------------

    @contextmanager
    def bucket(self, key: Optional[str]):
        """Tag compiles fired inside the block with ``key`` — exact
        because XLA compiles synchronously on the dispatching thread."""
        prev = getattr(self._tls, "bucket", None)
        self._tls.bucket = key
        try:
            yield
        finally:
            self._tls.bucket = prev

    # -- capture sink --------------------------------------------------------

    def _on_event(self, kind: str, name: str, secs: Optional[float]) -> None:
        bucket = getattr(self._tls, "bucket", None)
        if kind == "compiling":
            with self._lock:
                self.events += 1
                self.by_fn[name] = self.by_fn.get(name, 0) + 1
            if self._c_events is not None:
                self._c_events.inc()
            if self.metrics is not None:
                self.metrics.counter(f"compile.{_metric_safe(name)}").inc()
        elif kind == "finished" and self.recorder is not None:
            # one recorder event per compile, on the message that knows
            # the duration; a steady-state retrace therefore rides every
            # crash dump and /recorder pull with its cost attached
            self.recorder.record(
                "compile",
                fn=name,
                bucket=bucket,
                duration_ms=round(secs * 1e3, 3) if secs is not None else None,
            )

    # -- read side -----------------------------------------------------------

    def count(self, name: str) -> int:
        with self._lock:
            return self.by_fn.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"events": self.events, "by_fn": dict(self.by_fn)}
