"""Window-lifecycle span tracing (ISSUE 9 tentpole).

Answers "where did window W spend its 0.6 s between first row and
exported score" — the attribution every next perf tentpole (process-mode
ingest, Pallas fused aggregation, multi-tenant isolation) is gated on.
Each emitted window carries one span: named per-stage durations through
the full lifecycle,

    first-row-seen ──────────────► close begins          = ``scatter``
    per-shard close pop+aggregate                        = ``shard_close``
    cross-shard recombine / grouped reduction            = ``merge``
    feature assembly + pad/bucket                        = ``assemble``
    degree-cap sampling decision + selection (cap>0)     = ``sample``
    host→device: arrays/arena/transfer dispatch          = ``stage``
    device compute (blocked on)                          = ``score``
    score export ack (annotate + sink)                   = ``export``

Cost discipline (the ≤2 % rows/s bench bound): tracer calls happen per
**window × stage** (plus one ``first_row`` per chunk×window at the
persist mouth), never per row; each call is a dict write under one
short tracer lock; the lock-striped histograms are fed once per window
at completion, not per observation. ``enabled=False`` short-circuits
every method at the first branch.

The live-span map is bounded (``max_live``, LRU-evicted with a counter):
a window that never completes — scoring disabled mid-run, a shed window
queue — costs an eviction tick, not a leak.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from alaz_tpu.obs.histogram import Histogram

# ordered as the lifecycle runs; the e2e gate asserts every emitted
# window's span covers all of these. ``sample`` is always timed — with
# no degree cap it measures the cap *decision* (one branch), so the
# stage is nonzero in every pipeline and the completeness gate needs no
# cap-conditional carve-out.
STAGES = (
    "scatter",
    "shard_close",
    "merge",
    "assemble",
    "sample",
    "stage",
    "score",
    "export",
)

# the host-plane prefix: what a pipeline with no scorer behind it (bench
# ingest, the chaos harness — ``complete_at_emit=True``) can complete
HOST_STAGES = STAGES[:5]


class WindowSpan:
    __slots__ = ("window_start_ms", "t_first", "stages")

    def __init__(self, window_start_ms: int, t_first: float):
        self.window_start_ms = int(window_start_ms)
        self.t_first = t_first  # monotonic first-row-seen
        self.stages: Dict[str, float] = {}

    def missing(self, expected=STAGES) -> tuple:
        return tuple(s for s in expected if s not in self.stages)


class SpanTracer:
    """Per-window span registry + per-stage latency histograms.

    ``metrics``: a runtime ``Metrics`` registry — histograms register as
    ``latency.<stage>_s`` with counters ``trace.windows`` /
    ``trace.evicted`` and gauge ``trace.live``; with ``metrics=None``
    (bench A/B, chaos harness) the tracer keeps private histograms in
    ``self.hists``.

    ``complete_at_emit``: pipelines with no scorer behind them (bench
    ingest, the chaos harness) complete spans when the window emits;
    the service keeps spans open through score + export instead.
    """

    def __init__(
        self,
        metrics=None,
        recorder=None,
        enabled: bool = True,
        max_live: int = 4096,
        complete_at_emit: bool = False,
    ):
        self.enabled = enabled
        self.recorder = recorder
        self.complete_at_emit = complete_at_emit
        self.max_live = max(16, int(max_live))
        self._lock = threading.Lock()
        self._live: "OrderedDict[int, WindowSpan]" = OrderedDict()  # guarded-by: self._lock
        self.completed = 0  # guarded-by: self._lock
        self.evicted = 0  # guarded-by: self._lock
        if metrics is not None:
            self.hists = {
                s: metrics.histogram(f"latency.{s}_s") for s in STAGES
            }
            self._c_windows = metrics.counter("trace.windows")
            self._c_evicted = metrics.counter("trace.evicted")
            metrics.gauge("trace.live", lambda: self.live_count)
        else:
            self.hists = {s: Histogram(f"latency.{s}_s") for s in STAGES}
            self._c_windows = None
            self._c_evicted = None

    # -- lifecycle marks -----------------------------------------------------

    def _get_or_create_locked(self, w: int, now: float) -> WindowSpan:
        # contract: every caller holds self._lock (the `_locked` suffix);
        # the lint only models `with` blocks, hence the disables
        span = self._live.get(w)  # alazlint: disable=ALZ010 -- caller holds self._lock (_locked contract)
        if span is not None:
            # touch = recency: without this the eviction is FIFO and an
            # actively-observed straggler (the oldest window, mid-score)
            # is evicted FIRST while idle newer spans survive
            self._live.move_to_end(w)  # alazlint: disable=ALZ010 -- caller holds self._lock (_locked contract)
        else:
            if len(self._live) >= self.max_live:  # alazlint: disable=ALZ010 -- caller holds self._lock (_locked contract)
                self._live.popitem(last=False)  # alazlint: disable=ALZ010 -- caller holds self._lock (_locked contract)
                self.evicted += 1  # alazlint: disable=ALZ010 -- caller holds self._lock (_locked contract)
                if self._c_evicted is not None:
                    self._c_evicted.inc()
            span = WindowSpan(w, now)
            self._live[w] = span  # alazlint: disable=ALZ010 -- caller holds self._lock (_locked contract)
        return span

    def first_row(self, window_start_ms: int, t: Optional[float] = None) -> None:
        """First row of the window seen at the persist mouth; idempotent
        (only the first call sets the span's origin). ``t`` lets a
        cross-process pipeline (alaz_tpu/shm, ISSUE 15) backdate the
        origin to the shard worker's own CLOCK_MONOTONIC stamp — the
        clock is system-wide, so the residency math still closes."""
        if not self.enabled:
            return
        w = int(window_start_ms)
        now = time.perf_counter() if t is None else float(t)
        with self._lock:
            self._get_or_create_locked(w, now)

    def close_start(self, window_start_ms: int, t: Optional[float] = None) -> None:
        """The close wave reached this window: the elapsed time since
        first_row becomes the ``scatter`` stage (open-window residency —
        ingest, queueing, watermark wait). First caller wins; the other
        shards' close pops are covered by ``shard_close``. ``t`` as in
        :meth:`first_row` — the process backend stamps close time on the
        worker's clock."""
        if not self.enabled:
            return
        w = int(window_start_ms)
        now = time.perf_counter() if t is None else float(t)
        with self._lock:
            span = self._get_or_create_locked(w, now)
            if "scatter" not in span.stages:
                span.stages["scatter"] = now - span.t_first

    def observe(self, window_start_ms: int, stage: str, dur_s: float) -> None:
        """Record a stage duration on the window's span. Re-observation
        keeps the max — per-shard parallel closes all report, and the
        span carries the critical-path one."""
        if not self.enabled:
            return
        w = int(window_start_ms)
        with self._lock:
            span = self._get_or_create_locked(w, time.perf_counter())
            if stage not in span.stages or dur_s > span.stages[stage]:
                span.stages[stage] = dur_s

    def emit(self, window_start_ms: int) -> None:
        """The window's GraphBatch left the host plane. Completes the
        span when nothing downstream (scorer/export) will."""
        if self.enabled and self.complete_at_emit:
            self.complete(window_start_ms)

    def complete(self, window_start_ms: int) -> Optional[WindowSpan]:
        """Finalize: feed every stage duration into its histogram (one
        sample per window per stage), push the span event to the flight
        recorder, drop the live entry."""
        if not self.enabled:
            return None
        w = int(window_start_ms)
        with self._lock:
            span = self._live.pop(w, None)
            if span is None:
                return None
            self.completed += 1
        # histogram/recorder feeds run OUTSIDE the tracer lock: the
        # stripes have their own locks and the recorder its own ring lock
        for stage, dur in span.stages.items():
            h = self.hists.get(stage)
            if h is not None:
                h.observe(dur)
        if self._c_windows is not None:
            self._c_windows.inc()
        if self.recorder is not None:
            self.recorder.record(
                "window_span",
                window_start_ms=w,
                stages={s: round(d * 1e3, 4) for s, d in span.stages.items()},
            )
        return span

    def discard(self, window_start_ms: int) -> None:
        """Drop a live span without completing it (shed window)."""
        if not self.enabled:
            return
        with self._lock:
            self._live.pop(int(window_start_ms), None)

    # -- read side -----------------------------------------------------------

    @property
    def expected_stages(self) -> tuple:
        """The stages a complete span must carry in THIS pipeline: the
        host prefix when spans complete at emit (no scorer behind the
        tracer), the full lifecycle otherwise."""
        return HOST_STAGES if self.complete_at_emit else STAGES

    @property
    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    def stage_snapshot(self) -> dict:
        """{stage: {count, p50_ms, p95_ms, p99_ms}} — the /stats and
        bench ``stage_latency`` payload."""
        out = {}
        for s in STAGES:
            h = self.hists[s]
            snap = h.snapshot()
            out[s] = {
                "count": snap["count"],
                "p50_ms": round(snap["p50"] * 1e3, 4),
                "p95_ms": round(snap["p95"] * 1e3, 4),
                "p99_ms": round(snap["p99"] * 1e3, 4),
            }
        return out
