"""Lock-striped, mergeable log-bucket latency histogram (ISSUE 9).

The metrics registry had counters and gauges; per-stage latency needs a
*distribution* — p50/p95/p99 of "how long did the merge stage take" is
the number that gates every future perf claim (FeatGraph-style kernel
wins and multi-tenant isolation are per-stage, per-percentile
statements). Design constraints, in order:

1. **Cheap on the hot path.** ``observe`` is one bisect over ~30
   geometric bucket bounds plus three adds under a *striped* lock —
   each thread is round-robin-assigned one of ``N_STRIPES`` independent
   (lock, counts) cells at first use (a thread-local; see
   ``_stripe_index`` for why modulo-by-ident is a trap), so N shard
   workers recording concurrently never contend on one global lock
   (the ALZ042 discipline: the ingest surface must not gain a
   contended blocking point).
2. **Mergeable.** Buckets are a fixed geometric ladder shared by every
   instance, so histograms merge by vector addition — associative and
   commutative, which is what lets per-worker or per-tenant histograms
   fold into one fleet view (tested: merge order is invisible).
3. **Bounded error.** Buckets grow by 2×, percentiles interpolate
   linearly inside the bucket, so any reported quantile q satisfies
   ``true/2 <= q <= true*2`` — a factor-two band, constant memory,
   no reservoir, no decay bookkeeping.

Prometheus exposition follows the histogram text format (cumulative
``_bucket{le=...}`` + ``_sum`` + ``_count``), rendered by the metrics
registry next to its gauges.
"""

from __future__ import annotations

import itertools
import threading
from bisect import bisect_left
from typing import List, Optional, Sequence

# 1 µs .. ~537 s in 2× steps: spans every plausible stage latency from a
# sub-microsecond sample decision to a wedged close wave. The ladder is
# the merge contract — every Histogram shares it unless a caller opts
# into custom bounds (and then only merges with like-bounded peers).
DEFAULT_BOUNDS = tuple(1e-6 * (2.0 ** i) for i in range(30))

N_STRIPES = 8

# Stripe selection is a round-robin thread-local, NOT `get_ident() % N`:
# on Linux CPython the ident is the pthread_t — a stack address aligned
# to multi-MB boundaries — so the modulo maps EVERY thread to stripe 0
# and the striping silently degrades to one global contended lock
# (caught in review; regression-tested). First use assigns the thread
# the next index; every later observe is one thread-local read.
_stripe_tls = threading.local()
_stripe_counter = itertools.count()


def _stripe_index() -> int:
    idx = getattr(_stripe_tls, "idx", None)
    if idx is None:
        # itertools.count.__next__ is atomic in CPython; one call per
        # thread lifetime, so contention here is immaterial anyway
        idx = next(_stripe_counter) % N_STRIPES
        _stripe_tls.idx = idx
    return idx


class _Stripe:
    __slots__ = ("lock", "counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.lock = threading.Lock()
        self.counts = [0] * n_buckets  # guarded-by: self.lock
        self.sum = 0.0  # guarded-by: self.lock
        self.count = 0  # guarded-by: self.lock


class Histogram:
    """Thread-safe log-bucket histogram; see module docstring."""

    __slots__ = ("name", "bounds", "_stripes")

    def __init__(self, name: str = "", bounds: Optional[Sequence[float]] = None):
        self.name = name
        self.bounds = tuple(bounds) if bounds is not None else DEFAULT_BOUNDS
        # +1: the overflow bucket (> last bound, le="+Inf")
        self._stripes = [_Stripe(len(self.bounds) + 1) for _ in range(N_STRIPES)]

    # -- hot path ------------------------------------------------------------

    def observe(self, v: float) -> None:
        v = float(v)
        if v < 0.0:  # clock skew / monotonic misuse: clamp, never throw
            v = 0.0
        i = bisect_left(self.bounds, v)
        s = self._stripes[_stripe_index()]
        with s.lock:
            s.counts[i] += 1
            s.sum += v
            s.count += 1

    # -- read side -----------------------------------------------------------

    def _merged(self) -> tuple:
        counts = [0] * (len(self.bounds) + 1)
        total = 0
        vsum = 0.0
        for s in self._stripes:
            with s.lock:
                for i, c in enumerate(s.counts):
                    counts[i] += c
                total += s.count
                vsum += s.sum
        return counts, total, vsum

    @property
    def total_count(self) -> int:
        return self._merged()[1]

    @property
    def total_sum(self) -> float:
        return self._merged()[2]

    def bucket_counts(self) -> List[int]:
        """Non-cumulative per-bucket counts (len(bounds)+1, last=+Inf)."""
        return self._merged()[0]

    def percentile(self, q: float) -> float:
        """q∈[0,1] quantile, linearly interpolated inside its bucket.
        Error bound: within the containing bucket, i.e. a factor of the
        bucket growth (2×) of the true order statistic."""
        counts, total, _ = self._merged()
        return self._percentile_from(counts, total, q)

    def _percentile_from(self, counts: Sequence[int], total: int, q: float) -> float:
        # percentile over an already-merged view: snapshot() merges the
        # stripes ONCE and derives count + p50/p95/p99 from that single
        # instant (four independent merges would quadruple read-side
        # lock traffic and let count disagree with the percentiles)
        if total == 0:
            return 0.0
        target = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
                if hi <= lo:  # overflow bucket: report the last bound
                    return lo
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.bounds[-1]

    def snapshot(self) -> dict:
        counts, total, vsum = self._merged()
        out = {"count": total, "sum": vsum}
        for q, key in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
            out[key] = self._percentile_from(counts, total, q)
        return out

    def add_counts(self, counts: Sequence[int], vsum: float) -> None:
        """Fold an already-bucketed batch of observations in — the
        vectorized batch-observe path (obs/scores.py buckets a whole
        per-window score vector with one searchsorted+bincount instead
        of E bisects). ``counts`` is NON-cumulative per-bucket counts of
        length ``len(bounds)+1`` (last = overflow); ``vsum`` the sum of
        the raw values. Exactly equivalent to observing each value
        individually (tested), so merged sketches stay associative."""
        if len(counts) != len(self.bounds) + 1:
            raise ValueError("counts length must be len(bounds)+1")
        total = 0
        s = self._stripes[_stripe_index()]
        with s.lock:
            for i, c in enumerate(counts):
                c = int(c)
                s.counts[i] += c
                total += c
            s.count += total
            s.sum += float(vsum)

    # -- merge (associative: shared ladder, vector addition) -----------------

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other``'s state into self (in place); returns self."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bucket ladders")
        counts, total, vsum = other._merged()
        s = self._stripes[0]
        with s.lock:
            for i, c in enumerate(counts):
                s.counts[i] += c
            s.count += total
            s.sum += vsum
        return self

    def copy(self) -> "Histogram":
        out = Histogram(self.name, self.bounds)
        out.merge(self)
        return out

    # -- exposition ----------------------------------------------------------

    def render_prometheus(self, metric: str) -> List[str]:
        """Prometheus histogram text lines: cumulative buckets, sum,
        count (the node_exporter histogram shape)."""
        counts, total, vsum = self._merged()
        lines = [f"# TYPE {metric} histogram"]
        cum = 0
        for i, bound in enumerate(self.bounds):
            cum += counts[i]
            lines.append(f'{metric}_bucket{{le="{format(bound, ".9g")}"}} {cum}')
        lines.append(f'{metric}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{metric}_sum {format(vsum, '.9g')}")
        lines.append(f"{metric}_count {total}")
        return lines
