"""The observability plane (ISSUES 9 + 11): window-lifecycle span
tracing, lock-striped log-bucket latency histograms, a crash-safe
flight recorder, and the device-side telemetry plane.

- :mod:`alaz_tpu.obs.histogram` — ``Histogram``: mergeable, lock-striped
  log-bucket distribution with p50/p95/p99 and Prometheus histogram
  exposition (registered via ``Metrics.histogram``).
- :mod:`alaz_tpu.obs.spans` — ``SpanTracer``: per-window spans through
  the named lifecycle stages (first-row → scatter → shard close → merge
  → assemble → sample → host→device stage → device score → export ack).
- :mod:`alaz_tpu.obs.recorder` — ``FlightRecorder``: bounded ring of
  structured events, dumped automatically on worker crash and attached
  to chaos-gate failures.
- :mod:`alaz_tpu.obs.device` — ``DeviceTelemetry`` +
  ``CompileEventPlane``: per-bucket score latency/occupancy, the
  stage arena/transfer decomposition with a byte ledger, pad-waste
  accounting, and the always-on XLA compile event hookup.
- :mod:`alaz_tpu.obs.scores` — ``ScorePlane`` + ``DriftDetector``
  (ISSUE 13): per-model streaming score-distribution sketches on the
  [0,1] ladder, PSI/L∞-on-CDF drift detection with hysteresis and
  churn-triggered rebaselining, and the bounded top-K anomaly
  attribution ledger (``/scores``, ``/scores/top``).

Config: ``TRACE_*`` / ``RECORDER_*`` / ``DEVICE_TRACE_*`` /
``SCORE_TRACE_*`` / ``PROFILE_*`` env vars (CONFIG.md, TraceConfig).
Design notes: ARCHITECTURE §3m (host plane), §3n (device plane) and
§3p (score plane).
"""

from alaz_tpu.obs.device import (
    CompileEventPlane,
    DeviceTelemetry,
    batch_pad_waste_pct,
    bucket_key,
)
from alaz_tpu.obs.histogram import DEFAULT_BOUNDS, Histogram
from alaz_tpu.obs.recorder import FlightRecorder
from alaz_tpu.obs.scores import (
    SCORE_BOUNDS,
    DriftDetector,
    ScorePlane,
    feature_scores,
)
from alaz_tpu.obs.spans import HOST_STAGES, STAGES, SpanTracer, WindowSpan

__all__ = [
    "DEFAULT_BOUNDS",
    "Histogram",
    "FlightRecorder",
    "HOST_STAGES",
    "STAGES",
    "SpanTracer",
    "WindowSpan",
    "CompileEventPlane",
    "DeviceTelemetry",
    "batch_pad_waste_pct",
    "bucket_key",
    "SCORE_BOUNDS",
    "DriftDetector",
    "ScorePlane",
    "feature_scores",
]
