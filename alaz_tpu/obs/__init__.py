"""The observability plane (ISSUE 9): window-lifecycle span tracing,
lock-striped log-bucket latency histograms, and a crash-safe flight
recorder.

- :mod:`alaz_tpu.obs.histogram` — ``Histogram``: mergeable, lock-striped
  log-bucket distribution with p50/p95/p99 and Prometheus histogram
  exposition (registered via ``Metrics.histogram``).
- :mod:`alaz_tpu.obs.spans` — ``SpanTracer``: per-window spans through
  the named lifecycle stages (first-row → scatter → shard close → merge
  → assemble → sample → host→device stage → device score → export ack).
- :mod:`alaz_tpu.obs.recorder` — ``FlightRecorder``: bounded ring of
  structured events, dumped automatically on worker crash and attached
  to chaos-gate failures.

Config: ``TRACE_*`` / ``RECORDER_*`` env vars (CONFIG.md, TraceConfig).
Design notes: ARCHITECTURE §3m.
"""

from alaz_tpu.obs.histogram import DEFAULT_BOUNDS, Histogram
from alaz_tpu.obs.recorder import FlightRecorder
from alaz_tpu.obs.spans import HOST_STAGES, STAGES, SpanTracer, WindowSpan

__all__ = [
    "DEFAULT_BOUNDS",
    "Histogram",
    "FlightRecorder",
    "HOST_STAGES",
    "STAGES",
    "SpanTracer",
    "WindowSpan",
]
