"""Flight recorder: a bounded ring of structured runtime events (ISSUE 9).

Chaos-suite failures used to arrive as a bare assertion ("conservation
gap=412") with the run's history already gone. The flight recorder keeps
the last-N structured events — window closes with stage timings, worker
crashes/restarts, breaker flips, shed/ledger decisions, chaos
injections — in a fixed ring, so any gate failure or worker crash comes
with a replayable trail instead of a post-mortem guess.

Contract:

- ``record(kind, **fields)`` is O(1) under one short lock (a dict build
  plus a slot write; the ring never grows, never allocates after
  construction beyond the event dicts themselves) — cheap enough to sit
  on drop paths and close waves, NOT on per-row paths.
- events carry a global ``seq`` and wall-clock ``t``; ``events()``
  returns the surviving window oldest→newest, so a dump reads as a
  story.
- ``crash_dump(logger, reason)`` writes the formatted tail to the log
  (gated by ``dump_on_crash``); the sharded supervisor calls it when a
  worker dies, the chaos harness attaches ``dump()`` to failing
  reports, and the debug HTTP server serves it at ``/recorder``.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 512,
        metrics=None,
        dump_on_crash: bool = True,
    ):
        self.capacity = max(1, int(capacity))
        self.dump_on_crash = dump_on_crash
        self._buf: List[Optional[dict]] = [None] * self.capacity  # guarded-by: self._lock
        self._n = 0  # total ever recorded  # guarded-by: self._lock
        self._lock = threading.Lock()
        if metrics is not None:
            metrics.gauge("recorder.recorded", lambda: self.recorded)
            metrics.gauge("recorder.overwritten", lambda: self.overwritten)

    # envelope keys the recorder owns; caller fields with these names are
    # kept under a ``field_`` prefix instead of colliding (a field named
    # ``kind`` used to TypeError — and get swallowed by worker poison
    # nets — while ``t``/``seq`` silently corrupted event ordering)
    _RESERVED = ("kind", "t", "seq")

    def record(self, _kind: str, **fields) -> None:
        ev = {
            (f"field_{k}" if k in self._RESERVED else k): v
            for k, v in fields.items()
        }
        ev["kind"] = _kind
        with self._lock:
            # t stamped under the ring lock: seq order and t order must
            # agree, or a dump's oldest→newest story shows time running
            # backwards across concurrently-recording workers
            ev["t"] = round(time.time(), 6)
            ev["seq"] = self._n
            self._buf[self._n % self.capacity] = ev
            self._n += 1

    @property
    def recorded(self) -> int:
        with self._lock:
            return self._n

    @property
    def overwritten(self) -> int:
        """Events that fell off the ring (recorded - retained)."""
        with self._lock:
            return max(0, self._n - self.capacity)

    def events(self) -> List[dict]:
        """Surviving events, oldest→newest."""
        with self._lock:
            start = max(0, self._n - self.capacity)
            return [dict(self._buf[i % self.capacity]) for i in range(start, self._n)]

    def dump(self) -> dict:
        evs = self.events()
        return {
            "capacity": self.capacity,
            "recorded": self.recorded,
            "events": evs,
        }

    def dump_text(self, last: Optional[int] = None) -> str:
        evs = self.events()
        if last is not None:
            evs = evs[-last:]
        lines = []
        for e in evs:
            extra = " ".join(
                f"{k}={v}" for k, v in e.items() if k not in ("seq", "t", "kind")
            )
            lines.append(f"  #{e['seq']} t={e['t']:.3f} {e['kind']} {extra}".rstrip())
        return "\n".join(lines)

    def tail_summary(self, last: int = 64) -> str:
        """``last N of M events:\\n<tail>`` — the ONE framing shared by
        the crash dump and the chaos-gate warning (two hand-maintained
        copies would drift)."""
        shown = min(last, self.capacity, self.recorded)  # ring keeps ≤ capacity
        return (
            f"last {shown} of {self.recorded} events:\n"
            f"{self.dump_text(last=last)}"
        )

    def crash_dump(self, logger, reason: str, last: int = 64) -> None:
        """Write the tail of the ring to ``logger`` — the automatic
        worker-crash path. No-op when ``dump_on_crash`` is off."""
        if not self.dump_on_crash:
            return
        logger.error(
            f"flight recorder dump ({reason}): {self.tail_summary(last)}"
        )
