"""Score-plane observability (ISSUE 13): streaming score-distribution
sketches, drift detection, and top-K anomaly attribution.

PR 9 explained the pipeline's latency and PR 10 opened the device black
box, but the system's *product* — the anomaly scores — was still
unobserved: nothing watched score distributions in production, noticed a
model/topology change moving them, or could answer "why did node X score
0.97". This module is the third leg of the observability plane:

- **Streaming distribution sketches.** Every scored window's edge scores
  fold into a per-model mergeable sketch: the lock-striped
  :class:`~alaz_tpu.obs.histogram.Histogram` ladder remapped to [0,1]
  score space (:data:`SCORE_BOUNDS` — factor-2 log-odds rungs, fine at
  BOTH tails, where anomaly mass lives). One ``searchsorted`` +
  ``bincount`` per window buckets the whole vector; the same count
  vector then feeds the sketch (``Histogram.add_counts``) AND the drift
  compare, so the two can never disagree about what the window looked
  like. Per-window summary gauges (mean/p99/max score, scored-node
  count) ride next to the sketch on ``/metrics`` and ``/scores``.

- **Drift detection** (:class:`DriftDetector`). A rolling reference —
  the trailing K windows' bucket counts — is compared against each new
  window via PSI and L∞-on-CDF, with hysteresis on both edges (enter
  needs ``hysteresis`` consecutive over-threshold windows, exit needs
  the same run under HALF the threshold — a window hovering at the
  line cannot flap the state). Deploy-rollout-shaped node-table churn
  (a large fraction of the previous window's ACTIVE uids vanishing)
  **rebaselines** instead of alarming: the reference resets and refills
  before comparisons resume. The reference is trailing, so a sustained
  regime change pages for ~K windows and then becomes the new baseline
  (page-then-adapt). Flips and rebaselines land in the FlightRecorder
  and on the ``scores.drift_state`` gauge.

- **Top-K anomaly attribution.** Per window, the K highest-scoring
  nodes (node score = max over its in-edge scores — the dst-major
  aggregates assembly already produced) are kept in a bounded ledger
  with their feature z-scores against the window's ACTIVE-node
  population and their top contributing in-edges (src, protocol,
  score, request count, error rate). Bounded by construction — K nodes
  × E edges × W windows, never a per-node metric series — served at
  ``/scores/top`` and attached to scenario drift-gate failures.

Cost discipline (the ≤2 % ``score_plane_overhead_pct`` bench bound):
every observation is one vectorized pass per **window**, never per-row
Python; the only lock is the plane's own, once per window.

:func:`feature_scores` is the deterministic feature-space scorer the
scenario drift gates and the bench A/B share: a fixed logistic read of
the aggregated edge stats (error rates dominant, latency and volume
secondary), so scores move iff the windowed stats move — no trained
model needed to prove the drift machinery end to end on CPU.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, List, Optional

import numpy as np

from alaz_tpu.obs.histogram import Histogram

# ---------------------------------------------------------------------------
# The score-space ladder: the Histogram contract (fixed shared bounds →
# merge is vector addition) remapped to [0,1]. Factor-2 rungs from 1e-4
# up to 0.4096, a 0.5 midpoint, then the mirror approaching 1 — log-odds
# resolution at both tails, where "almost surely fine" and "almost
# surely anomalous" mass concentrates. 28 bounds + overflow.
# ---------------------------------------------------------------------------

_TAIL = tuple(1e-4 * (2.0**i) for i in range(13))  # 1e-4 .. 0.4096
SCORE_BOUNDS = (
    _TAIL + (0.5,) + tuple(round(1.0 - b, 10) for b in reversed(_TAIL)) + (1.0,)
)
N_SCORE_BUCKETS = len(SCORE_BOUNDS) + 1

_BOUNDS_F8 = np.asarray(SCORE_BOUNDS, dtype=np.float64)

# Bucketing is the plane's hottest op (once per edge score per window),
# and np.searchsorted pays ~17ns/element of generic binary-search
# overhead. The ladder is FIXED, so bucket lookup is a uniform
# quantization table instead: cell = floor(score * 65536), bucket =
# table[cell] — one multiply, one cast, one gather. Cells that contain
# a ladder rung (or neighbor one — the float32 multiply can land a
# value one cell over at a cell edge) are marked ambiguous and fall
# back to exact searchsorted for just those elements, so the result is
# bit-identical to bisect_left for EVERY input (the parity test sweeps
# the rungs and their float neighborhoods).
_CELL_BITS = 16
_N_CELLS = 1 << _CELL_BITS


def _build_cell_tables():
    edges = np.arange(_N_CELLS + 1, dtype=np.float64) / _N_CELLS
    lo = np.searchsorted(_BOUNDS_F8, edges[:-1], side="left")
    hi = np.searchsorted(
        _BOUNDS_F8, np.nextafter(edges[1:], -1.0), side="left"
    )
    amb = lo != hi
    amb = amb | np.roll(amb, 1) | np.roll(amb, -1)
    return lo.astype(np.intp), amb


_CELL_TABLE, _CELL_AMBIGUOUS = _build_cell_tables()


def score_bucket_counts(scores: np.ndarray) -> np.ndarray:
    """One window's scores → per-bucket counts on the score ladder;
    exactly ``bisect_left(SCORE_BOUNDS, v)`` per value (what
    ``Histogram.observe`` computes) for v in the score domain [0, 1],
    via the quantization table above. Out-of-domain values clamp into
    the end buckets — score space is closed, the overflow bucket of the
    generic Histogram ladder is dead weight here."""
    if scores.size == 0:
        return np.zeros(N_SCORE_BUCKETS, dtype=np.intp)
    q = np.clip((scores * _N_CELLS).astype(np.intp), 0, _N_CELLS - 1)
    idx = _CELL_TABLE[q]
    amb = _CELL_AMBIGUOUS[q]
    if amb.any():
        idx[amb] = np.searchsorted(
            _BOUNDS_F8, scores[amb].astype(np.float64), side="left"
        )
    return np.bincount(idx, minlength=N_SCORE_BUCKETS)


# ---------------------------------------------------------------------------
# Distribution distance: PSI + L∞-on-CDF over the shared ladder.
# ---------------------------------------------------------------------------


def psi(
    ref_counts: np.ndarray, cur_counts: np.ndarray, floor: float = 5e-3
) -> float:
    """Population stability index between two count vectors on the same
    ladder. Proportions are FLOORED (the standard PSI smoothing), not
    epsilon-added: a tiny epsilon lets a 2% sliver of mass opposite an
    empty bucket contribute ``0.02·ln(0.02/1e-7) ≈ 0.25`` — a full
    alarm threshold of phantom drift from one straggler bucket, exactly
    the noise small service-map windows (tens of edges) produce. With
    the floor, an absent-vs-2% bucket costs ~0.03 and real regime
    shifts (half the mass moving rungs) still score ≥1."""
    ref = np.asarray(ref_counts, dtype=np.float64)
    cur = np.asarray(cur_counts, dtype=np.float64)
    rt, ct = ref.sum(), cur.sum()
    if rt <= 0 or ct <= 0:
        return 0.0
    p = np.maximum(ref / rt, floor)
    q = np.maximum(cur / ct, floor)
    return float(np.sum((q - p) * np.log(q / p)))


def cdf_linf(ref_counts: np.ndarray, cur_counts: np.ndarray) -> float:
    """L∞ distance between the two empirical CDFs on the shared ladder
    (the Kolmogorov–Smirnov statistic at bucket resolution): catches a
    mass SHIFT that PSI's per-bucket terms understate when the mass
    slides across many adjacent rungs."""
    ref = np.asarray(ref_counts, dtype=np.float64)
    cur = np.asarray(cur_counts, dtype=np.float64)
    rt, ct = ref.sum(), cur.sum()
    if rt <= 0 or ct <= 0:
        return 0.0
    return float(np.abs(np.cumsum(ref) / rt - np.cumsum(cur) / ct).max())


STABLE, DRIFTED = 0, 1


class DriftDetector:
    """Rolling-reference drift state machine (see module docstring).

    NOT internally locked: the owning :class:`ScorePlane` serializes
    every call under its plane lock (one update per window)."""

    def __init__(
        self,
        window: int = 8,
        enter_psi: float = 0.25,
        enter_ks: float = 0.2,
        hysteresis: int = 2,
        min_ref: Optional[int] = None,
        exit_frac: float = 0.5,
    ):
        self.window = max(1, int(window))
        self.enter_psi = float(enter_psi)
        self.enter_ks = float(enter_ks)
        self.hysteresis = max(1, int(hysteresis))
        # windows the reference must hold before comparisons start (a
        # fresh or just-rebaselined plane accumulates, never judges)
        self.min_ref = self.window if min_ref is None else max(1, int(min_ref))
        self.exit_frac = float(exit_frac)
        self._ref: Deque[np.ndarray] = deque(maxlen=self.window)
        self.state = STABLE
        self.flips = 0  # stable→drifted transitions
        self.rebaselines = 0
        self.compared = 0
        self.last_psi = 0.0
        self.last_ks = 0.0
        self._over = 0  # consecutive over-threshold windows
        self._under = 0  # consecutive under-exit windows

    @property
    def reference_windows(self) -> int:
        return len(self._ref)

    def rebaseline(self) -> None:
        """Reset the reference (deploy-rollout-shaped churn): the new
        regime accumulates ``min_ref`` windows before judging resumes,
        and the state returns to stable with clean hysteresis counters."""
        self._ref.clear()
        self.rebaselines += 1
        self.state = STABLE
        self._over = self._under = 0

    def update(self, counts: np.ndarray) -> dict:
        """Fold one window in; returns {psi, ks, state, flipped,
        compared} where ``flipped`` is None / "drifted" / "stable"."""
        flipped = None
        compared = False
        if len(self._ref) >= self.min_ref:
            ref = np.sum(np.stack(list(self._ref)), axis=0)
            self.last_psi = psi(ref, counts)
            self.last_ks = cdf_linf(ref, counts)
            self.compared += 1
            compared = True
            over = self.last_psi > self.enter_psi or self.last_ks > self.enter_ks
            under = (
                self.last_psi < self.enter_psi * self.exit_frac
                and self.last_ks < self.enter_ks * self.exit_frac
            )
            if self.state == STABLE:
                self._over = self._over + 1 if over else 0
                if self._over >= self.hysteresis:
                    self.state = DRIFTED
                    self.flips += 1
                    flipped = "drifted"
                    self._over = 0
            else:
                self._under = self._under + 1 if under else 0
                if self._under >= self.hysteresis:
                    self.state = STABLE
                    flipped = "stable"
                    self._under = 0
        self._ref.append(np.asarray(counts, dtype=np.int64))
        return {
            "psi": self.last_psi,
            "ks": self.last_ks,
            "state": self.state,
            "flipped": flipped,
            "compared": compared,
        }


# ---------------------------------------------------------------------------
# Attribution vocabulary: the node-feature stat columns `_assemble`
# writes (graph/builder.py), named so a /scores/top reader doesn't need
# the builder source open to know what z=+38 on `in_count` means.
# ---------------------------------------------------------------------------

NODE_STAT_COLS = {
    "out_count": 4,
    "in_count": 5,
    "out_err_rate": 6,
    "in_err_rate": 7,
    "out_latency": 8,
    "in_latency": 9,
    "out_degree": 10,
    "in_degree": 11,
}


def feature_logits(edge_feats: np.ndarray) -> np.ndarray:
    """The fixed feature-space read in LOGIT form, vectorized over any
    leading shape — the ONE definition of the deterministic scorer's
    weights. :func:`feature_scores` is its sigmoid; the tenancy replay
    harness (replay/tenants.py) drives the service scorer loop with
    this directly, so the per-tenant planes see EXACTLY the
    feature_scores distribution by construction."""
    ef = np.asarray(edge_feats)
    return (
        6.0 * ef[..., 3]  # 5xx/error rate
        + 3.0 * ef[..., 4]  # 4xx rate
        + 2.0 * ef[..., 1]  # log mean latency (scaled /20 by assembly)
        + 0.5 * ef[..., 0]  # log1p request count
        - 4.0
    ).astype(np.float32)


def feature_scores(batch) -> np.ndarray:
    """The deterministic feature-space scorer the scenario drift gates
    and the bench A/B share: a FIXED logistic read of the aggregated
    edge features (5xx rate dominant, 4xx and latency secondary, volume
    mild) — a pure function of the windowed stats, so the score
    distribution moves iff the stats move, with no trained model (and no
    accelerator) in the loop. NOT a detection model: the real models
    score the service, this scores the *plane*."""
    z = feature_logits(batch.edge_feats[: batch.n_edges])
    return (1.0 / (1.0 + np.exp(-z))).astype(np.float32)


class ScorePlane:
    """The score-plane accountant for one scorer (see module docstring).

    ``metrics``: a runtime ``Metrics`` registry — the per-model sketch
    registers sparse as ``scores.dist.<model>`` (absent from the scrape
    until the first scored window), the summary/drift gauges and the
    ``scores.*`` counters register eagerly. ``enabled=False`` registers
    NOTHING and short-circuits every observe at the first branch (the
    SCORE_TRACE_ENABLED kill switch + the absent-not-zero discipline: a
    killed plane must be absent from the scrape, not render
    ``scores.drift_state 0`` as if it were watching).

    ``resolve``: optional uid→string resolver (the service passes
    ``interner.lookup``) so the attribution ledger carries names, not
    interned ids. One scorer thread writes; ``/scores`` handlers read —
    all mutable state sits under the plane lock, once per window.
    """

    def __init__(
        self,
        metrics=None,
        recorder=None,
        enabled: bool = True,
        model: str = "default",
        drift_windows: int = 8,
        top_k: int = 10,
        top_edges: int = 3,
        ledger_windows: int = 32,
        enter_psi: float = 0.25,
        enter_ks: float = 0.2,
        hysteresis: int = 2,
        min_ref: Optional[int] = None,
        rebaseline_frac: float = 0.25,
        resolve: Optional[Callable[[int], str]] = None,
        metric_suffix: str = "",
    ):
        self.enabled = bool(enabled)
        self.metrics = metrics if self.enabled else None
        self.recorder = recorder
        self.model = str(model) or "default"
        # tenancy (ISSUE 14): a per-tenant plane registers every series
        # under its own ``.t<k>`` suffix so K planes on one registry
        # never share a gauge/counter instance (same-name registration
        # returns the existing object — K unsuffixed planes would
        # silently sum their counters and last-write their gauges). ""
        # keeps the single-tenant names bit-for-bit.
        self._suffix = str(metric_suffix)
        self.top_k = max(0, int(top_k))
        self.top_edges = max(1, int(top_edges))
        self.rebaseline_frac = float(rebaseline_frac)
        self.resolve = resolve
        self._lock = threading.Lock()
        self._drift = DriftDetector(  # guarded-by: self._lock
            window=drift_windows,
            enter_psi=enter_psi,
            enter_ks=enter_ks,
            hysteresis=hysteresis,
            min_ref=min_ref,
        )
        # bounded attribution ring: K nodes × top_edges in-edges per
        # entry, last `ledger_windows` windows — never a per-node series
        self._ledger: Deque[dict] = deque(  # guarded-by: self._lock
            maxlen=max(1, int(ledger_windows))
        )
        self._prev_uids: Optional[np.ndarray] = None  # guarded-by: self._lock
        self.windows = 0  # guarded-by: self._lock
        self._last: dict = {}  # last-window summary  # guarded-by: self._lock
        if self.metrics is not None:
            # sparse: the sketch is absent from /metrics and snapshot
            # until the first scored window (the empty-series rule)
            self.hist = self.metrics.histogram(
                f"scores.dist.{self.model}{self._suffix}",
                sparse=True,
                bounds=SCORE_BOUNDS,
            )
            self._c_windows = self.metrics.counter(f"scores.windows{self._suffix}")
            self._c_drift = self.metrics.counter(
                f"scores.drift_events{self._suffix}"
            )
            self._c_rebase = self.metrics.counter(
                f"scores.rebaselines{self._suffix}"
            )
            # set-style gauges (no callbacks): the registry never calls
            # back into the plane, so no lock-order edge toward the
            # plane lock can form (the device plane's ABBA lesson)
            self._g_mean = self.metrics.gauge(f"scores.window_mean{self._suffix}")
            self._g_p99 = self.metrics.gauge(f"scores.window_p99{self._suffix}")
            self._g_max = self.metrics.gauge(f"scores.window_max{self._suffix}")
            self._g_nodes = self.metrics.gauge(
                f"scores.scored_nodes{self._suffix}"
            )
            self._g_state = self.metrics.gauge(f"scores.drift_state{self._suffix}")
            self._g_psi = self.metrics.gauge(f"scores.drift_psi{self._suffix}")
            self._g_ks = self.metrics.gauge(f"scores.drift_ks{self._suffix}")
        else:
            self.hist = Histogram(
                f"scores.dist.{self.model}{self._suffix}", bounds=SCORE_BOUNDS
            )
            self._c_windows = self._c_drift = self._c_rebase = None
            self._g_mean = self._g_p99 = self._g_max = None
            self._g_nodes = self._g_state = self._g_psi = self._g_ks = None

    # -- per-window observe (the scorer thread's one call) -------------------

    def observe_window(self, batch, scores: np.ndarray) -> None:
        """Fold one scored window in: sketch + summary + drift compare +
        attribution. ``scores`` are the window's REAL-edge scores in
        [0,1] (the sigmoid the export leg also reads), length
        ``batch.n_edges``."""
        if not self.enabled:
            return
        scores = np.asarray(scores)
        n = int(scores.shape[0])
        # cost discipline: everything below is O(E) vectorized with no
        # sort — counts via one searchsorted+bincount, the summary p99
        # straight from those counts (sketch resolution — np.quantile's
        # per-window sort was the plane's single biggest cost), active
        # nodes via degree bincounts instead of unique's sort
        counts = score_bucket_counts(scores)
        vsum = float(scores.sum(dtype=np.float64))
        if n:
            mean = vsum / n
            p99 = self.hist._percentile_from(counts, n, 0.99)
            mx = float(scores.max())
        else:
            mean = p99 = mx = 0.0
        # active nodes = endpoints touched by this window's edges: the
        # NodeTable is cumulative across windows, so churn/attribution
        # must read the window's live population, not the table
        if n:
            deg = np.bincount(batch.edge_src[:n], minlength=batch.n_pad)
            deg += np.bincount(batch.edge_dst[:n], minlength=batch.n_pad)
            active = np.flatnonzero(deg)
        else:
            active = np.empty(0, dtype=np.int64)
        if batch.node_uids is not None and active.size:
            # slot↔uid is bijective in the NodeTable, so the gather of
            # unique slots is already a unique uid set — no sort needed
            uids = batch.node_uids[active]
        else:
            uids = active
        entry = self._attribution(batch, scores, active) if self.top_k else None

        with self._lock:
            self.windows += 1
            rebased = False
            churn = 0.0
            if self._prev_uids is not None and self._prev_uids.size and uids.size:
                # disappearance, not addition: a rollout REPLACES nodes
                # (old uids vanish → rebaseline); a hot key / dns storm
                # ADDS nodes while the old ones keep talking (→ the
                # distribution compare stays armed and may page)
                churn = 1.0 - float(
                    np.isin(self._prev_uids, uids, assume_unique=True).mean()
                )
                if churn >= self.rebaseline_frac:
                    self._drift.rebaseline()
                    rebased = True
            if uids.size:
                # an EMPTY window (traffic gap) must not become the
                # churn baseline: a rollout separated from the old
                # regime by one idle window would then never compare
                # old-vs-new uids and would page as drift instead of
                # rebaselining (review-caught; regression-tested)
                self._prev_uids = uids
            d = self._drift.update(counts)
            if entry is not None:
                self._ledger.append(entry)
            self._last = {
                "window_start_ms": int(batch.window_start_ms),
                "scored_edges": n,
                "scored_nodes": int(active.size),
                "mean": round(mean, 4),
                "p99": round(p99, 4),
                "max": round(mx, 4),
            }

        # sketch + metric/recorder feeds run OUTSIDE the plane lock (the
        # histogram has its own stripe locks, the recorder its ring lock)
        self.hist.add_counts(counts.tolist(), vsum)
        if self.metrics is not None:
            self._c_windows.inc()
            self._g_mean.set(mean)
            self._g_p99.set(p99)
            self._g_max.set(mx)
            self._g_nodes.set(float(active.size))
            self._g_state.set(float(d["state"]))
            self._g_psi.set(d["psi"])
            self._g_ks.set(d["ks"])
            if rebased:
                self._c_rebase.inc()
            if d["flipped"] == "drifted":
                self._c_drift.inc()
        if self.recorder is not None:
            if rebased:
                self.recorder.record(
                    "score_rebaseline",
                    window_start_ms=int(batch.window_start_ms),
                    churn=round(churn, 4),
                )
            if d["flipped"] is not None:
                self.recorder.record(
                    "score_drift",
                    window_start_ms=int(batch.window_start_ms),
                    state=("drifted" if d["state"] == DRIFTED else "stable"),
                    psi=round(d["psi"], 4),
                    ks=round(d["ks"], 4),
                )

    # ONE bookkeeper for the drift events: the detector's own counters
    # (a plane-side copy incremented next to them would desynchronize
    # the moment any path touches the detector directly)

    @property
    def drift_events(self) -> int:
        """Stable→drifted flips observed (the scenario-gate count)."""
        with self._lock:
            return self._drift.flips

    @property
    def rebaselines(self) -> int:
        with self._lock:
            return self._drift.rebaselines

    # -- attribution ---------------------------------------------------------

    def _node_name(self, batch, slot: int):
        if batch.node_uids is None:
            return int(slot)
        uid = int(batch.node_uids[slot])
        if self.resolve is not None:
            try:
                return self.resolve(uid)
            except Exception:
                return uid
        return uid

    def _attribution(self, batch, scores: np.ndarray, active: np.ndarray) -> dict:
        """One window's top-K ledger entry: K highest-scoring nodes
        (node score = max in-edge score over the dst-major aggregates),
        feature z-scores vs the window's ACTIVE population, top
        contributing in-edges. Bounded: K × top_edges, whatever the
        fan-in (the 500k hot-key test pins this)."""
        n = int(scores.shape[0])
        entry = {
            "window_start_ms": int(batch.window_start_ms),
            "scored_edges": n,
            "scored_nodes": int(active.size),
            "nodes": [],
        }
        if n == 0 or active.size == 0:
            return entry
        e_dst = batch.edge_dst[:n]
        # node score = max over in-edge scores. The builder emits edges
        # DST-MAJOR sorted, so each node's in-edges are one contiguous
        # run: per-dst maxes are a single O(E) reduceat and a node's run
        # is two binary searches — no per-node full-array masks, no
        # ufunc.at. Hand-built unsorted batches take the general path.
        d = np.diff(e_dst)
        if not np.any(d < 0):
            starts = np.concatenate(([0], np.flatnonzero(d > 0) + 1))
            uniq_dst = e_dst[starts]
            dst_max = np.maximum.reduceat(scores, starts)
            ends = np.concatenate((starts[1:], [n]))
        else:
            node_score = np.zeros(batch.n_pad, dtype=np.float64)
            np.maximum.at(node_score, e_dst, scores)
            uniq_dst = np.flatnonzero(node_score > 0.0)
            dst_max = node_score[uniq_dst]
            starts = ends = None
        k = min(self.top_k, int(uniq_dst.size))
        sel_k = np.argpartition(dst_max, -k)[-k:]
        sel_k = sel_k[np.argsort(-dst_max[sel_k], kind="stable")]
        feats = batch.node_feats[active]
        mu = feats.mean(axis=0)
        sd = feats.std(axis=0)
        sd = np.where(sd > 1e-9, sd, 1.0)
        from alaz_tpu.events.schema import _PROTOCOL_NAMES as proto_names

        nodes: List[dict] = []
        for j in sel_k:
            slot = int(uniq_dst[j])
            s = float(dst_max[j])
            if s <= 0.0:
                continue  # a node with no scored in-edge explains nothing
            z = np.round((batch.node_feats[slot] - mu) / sd, 2)
            if starts is not None:
                idx = np.arange(starts[j], ends[j])
            else:
                idx = np.flatnonzero(e_dst == slot)
            if idx.size > self.top_edges:
                sel = idx[np.argpartition(scores[idx], -self.top_edges)[-self.top_edges:]]
            else:
                sel = idx
            sel = sel[np.argsort(-scores[sel], kind="stable")]
            edges = [
                {
                    "src": self._node_name(batch, int(batch.edge_src[i])),
                    "proto": proto_names[int(batch.edge_type[i]) % len(proto_names)],
                    "score": round(float(scores[i]), 4),
                    "requests": int(round(float(np.expm1(batch.edge_feats[i, 0])))),
                    "err_rate": round(float(batch.edge_feats[i, 3]), 4),
                }
                for i in sel
            ]
            nodes.append(
                {
                    "uid": self._node_name(batch, int(slot)),
                    "score": round(s, 4),
                    "in_edges_seen": int(idx.size),
                    "z": {
                        name: float(z[col])
                        for name, col in NODE_STAT_COLS.items()
                    },
                    "top_in_edges": edges,
                }
            )
        entry["nodes"] = nodes
        return entry

    # -- read side (the /scores surfaces) ------------------------------------

    def snapshot(self) -> dict:
        """The ``/scores`` payload: sketch percentiles, last-window
        summary, drift state — bounded, no per-node data (that is
        ``top_snapshot``'s job)."""
        with self._lock:
            out = {
                "model": self.model,
                "windows": self.windows,
                "last_window": dict(self._last),
                "drift": {
                    "state": "drifted" if self._drift.state == DRIFTED else "stable",
                    "psi": round(self._drift.last_psi, 4),
                    "ks": round(self._drift.last_ks, 4),
                    # the detector's own counters — read directly here
                    # (the public properties re-take the plane lock)
                    "events": self._drift.flips,
                    "rebaselines": self._drift.rebaselines,
                    "reference_windows": self._drift.reference_windows,
                    "compared": self._drift.compared,
                },
            }
        snap = self.hist.snapshot()  # stripe locks, outside the plane lock
        out["dist"] = {
            "count": snap["count"],
            "p50": round(snap["p50"], 4),
            "p95": round(snap["p95"], 4),
            "p99": round(snap["p99"], 4),
        }
        return out

    def top_snapshot(self, windows: int = 1) -> List[dict]:
        """The ``/scores/top`` payload: the newest ``windows`` ledger
        entries, newest first. Bounded by the ring size however large
        the ask."""
        w = max(0, int(windows))
        with self._lock:
            entries = list(self._ledger)[-w:] if w else []
        return list(reversed(entries))
