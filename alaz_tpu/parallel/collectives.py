"""Named collective wrappers over mesh axes.

The reference's "distributed communication backend" is an HTTP/JSON batch
plane (SURVEY §2.2 G17); here it is XLA collectives over the device mesh —
ICI within a slice, DCN across slices (§2.3 P7). These wrappers exist so
call sites name their intent (and so the halo/expert layers read like the
algorithms they implement); they are all trivially `jax.lax` under the
hood and only valid inside ``shard_map``/collective contexts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def all_reduce_sum(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    return jax.lax.psum(x, axis)


def all_gather(x: jnp.ndarray, axis: str, *, tiled: bool = True) -> jnp.ndarray:
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter_sum(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    return jax.lax.psum_scatter(x, axis, tiled=True)


def ring_shift(x: jnp.ndarray, axis: str, shift: int = 1) -> jnp.ndarray:
    """Rotate shards around the ring: device i's block goes to i+shift.
    The halo-exchange primitive (ppermute rides ICI neighbor links)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm=perm)


def all_to_all(x: jnp.ndarray, axis: str, split_axis: int, concat_axis: int) -> jnp.ndarray:
    """Ulysses-style resharding between node-sharded and feature-sharded
    layouts (SURVEY §2.3 P6)."""
    return jax.lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def nodes_to_features(h_local: jnp.ndarray, axis: str) -> jnp.ndarray:
    """[n_loc, F] node-sharded → [N, F/D] feature-sharded, one all-to-all
    (the P6 reshard between a halo/ring layer, which wants whole feature
    rows per node block, and a TP dense layer, which wants whole node
    columns per feature block). Inside shard_map only; F must divide by
    the axis size."""
    return all_to_all(h_local, axis, split_axis=1, concat_axis=0)


def features_to_nodes(h_local: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Inverse of nodes_to_features: [N, F/D] → [n_loc, F]."""
    return all_to_all(h_local, axis, split_axis=0, concat_axis=1)


def axis_index(axis: str) -> jnp.ndarray:
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    # lax.axis_size is newer than some supported jax releases; psum(1, axis)
    # is the long-standing equivalent (resolved to a concrete int at trace)
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)
