"""Microbatch pipeline parallelism over a mesh axis (SURVEY §2.3 P3).

The reference's "pipeline" is staged goroutine channels (ebpf→agg→ds);
the on-device analog for deep GNN stacks is GPipe-style microbatching:
each device along the ``pp`` axis owns one contiguous block of layers,
activations hop stage→stage via ``lax.ppermute`` (XLA lowers it onto
ICI), and the classic (M + S − 1)-tick schedule keeps every stage busy
once the pipe fills. Bubble fraction is (S−1)/(M+S−1) — choose M ≫ S.

This is deliberately model-agnostic: ``make_pipeline`` takes any
per-layer ``fn(layer_params, x) -> x`` plus layer params stacked on a
leading layer axis (a multiple of the stage count; each stage applies
its consecutive layer block), and returns a jitted function over
microbatched inputs. It is the scale-out path for GNN stacks deeper
than one device's memory allows; the unit tests validate it numerically
against the sequential loop on the 8-virtual-device CPU mesh, including
the layers-per-stage > 1 case.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alaz_tpu.parallel.mesh import shard_map


def make_pipeline(
    fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    axis: str = "sp",
) -> Callable:
    """Build ``run(stacked_layer_params, microbatches) -> outputs``.

    - ``stacked_layer_params``: pytree whose leaves have leading axis L
      (the layer count), a multiple of the mesh size S along ``axis``;
      stage s applies its L/S consecutive layers in order.
    - ``microbatches``: [M, ...] array; every microbatch flows through
      all L layers stage by stage.

    Schedule: at tick t ∈ [0, M+S−1), stage s applies ``fn`` to the
    activation of microbatch (t − s) when 0 ≤ t − s < M; activations then
    ppermute one hop toward the next stage. Stage 0 injects microbatch t
    at tick t; stage S−1's outputs are collected in tick order.
    """
    s_axis = mesh.shape[axis]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
    )
    def run(layer_params, micro):
        # shard_map hands each device its own stage slice with a leading
        # axis of size 1 (params) and its M/S shard of microbatches — but
        # the pipeline wants EVERY microbatch through EVERY stage, so the
        # microbatch axis is all-gathered here (cheap: activations are
        # the small thing in PP; params are what's partitioned)
        stage = jax.lax.axis_index(axis)
        layers_per_stage = jax.tree.leaves(layer_params)[0].shape[0]

        def apply_stage(x):
            for i in range(layers_per_stage):
                layer = jax.tree.map(lambda p: p[i], layer_params)
                x = fn(layer, x)
            return x

        micro_all = jax.lax.all_gather(micro, axis, axis=0, tiled=True)  # [M, ...]
        m = micro_all.shape[0]
        ticks = m + s_axis - 1
        perm = [(i, (i + 1) % s_axis) for i in range(s_axis)]

        def tick(t, carry):
            inflight, outputs = carry
            # stage 0 injects microbatch t; other stages use the hopped
            # activation from the previous tick
            mb_idx = jnp.clip(t, 0, m - 1)
            injected = micro_all[mb_idx]
            x = jnp.where(stage == 0, injected, inflight)
            active = (t - stage >= 0) & (t - stage < m)
            y = apply_stage(x)
            y = jnp.where(active, y, inflight)
            # the last stage's completed microbatch (t − (S−1)) lands in
            # the output buffer; other stages write garbage that their
            # out-slot masking discards
            out_idx = jnp.clip(t - (s_axis - 1), 0, m - 1)
            take = active & (stage == s_axis - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(take, y, outputs[out_idx]),
                out_idx,
                axis=0,
            )
            # hop activations one stage forward for the next tick
            inflight = jax.lax.ppermute(y, axis, perm)
            return inflight, outputs

        zero = jnp.zeros_like(micro_all[0])
        outputs0 = jnp.zeros_like(micro_all)
        _, outputs = jax.lax.fori_loop(0, ticks, tick, (zero, outputs0))
        # every device holds the full [M, ...] buffer but only the last
        # stage's is real; psum after zeroing the rest replicates it, and
        # the out_spec then hands each device its shard
        outputs = jnp.where(stage == s_axis - 1, outputs, jnp.zeros_like(outputs))
        outputs = jax.lax.psum(outputs, axis)
        return jax.lax.dynamic_slice_in_dim(
            outputs, stage * (m // s_axis), m // s_axis, axis=0
        )

    return jax.jit(run)


def sequential_reference(fn, stacked_layer_params, microbatches):
    """The ground truth: every microbatch through every layer in order."""
    s = jax.tree.leaves(stacked_layer_params)[0].shape[0]

    def one(x):
        for i in range(s):
            layer = jax.tree.map(lambda p: p[i], stacked_layer_params)
            x = fn(layer, x)
        return x

    return jax.vmap(one)(microbatches)
