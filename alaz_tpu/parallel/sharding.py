"""Sharding rules + the sharded train step.

Strategy (the scaling-book recipe: pick a mesh, annotate shardings, let
XLA insert collectives):

- **DP**: a step consumes a *stack* of G window graphs ``[G, ...]``; G is
  sharded over ``dp``. Gradients all-reduce over ``dp`` automatically
  (params are replicated over dp).
- **TP**: every dense ``w [in, out]`` shards its out-dim over ``tp``; the
  next layer contracts the sharded dim, so XLA places the reduce where the
  math needs it. Embedding/type tables shard over tp on the hidden dim.
- **EP/SP** are layered separately (experts.py routes by edge type;
  halo.py shards the node axis).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alaz_tpu.config import ModelConfig
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.models.registry import get_model
from alaz_tpu.train.objective import edge_bce_loss

# ---------------------------------------------------------------------------
# PartitionSpecs
# ---------------------------------------------------------------------------


def mesh_axis_names() -> tuple[str, ...]:
    """Re-export of config.mesh_axis_names (the single source of truth
    for the mesh vocabulary) for sharding-side callers."""
    from alaz_tpu.config import mesh_axis_names as _names

    return _names()


def param_pspec(params: Any, tp: int = 1, ep: int = 1) -> Any:
    """TP rule: 2D weights shard the output dim over 'tp' when divisible
    (heads ending in width-1 logits replicate); 1D params replicate.
    EP rule: stacked expert tables (``expert_*`` [T, ...]) shard the
    expert axis over 'ep'."""

    def rule(path: tuple, leaf) -> P:
        key_names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        is_expert = any(str(k).startswith("expert_") for k in key_names)
        if is_expert and ep > 1 and leaf.shape[0] % ep == 0:
            if leaf.ndim == 3 and tp > 1 and leaf.shape[-1] % tp == 0:
                return P("ep", None, "tp")
            return P("ep", *([None] * (leaf.ndim - 1)))
        if leaf.ndim == 2 and tp > 1 and leaf.shape[-1] % tp == 0:
            # type_emb [T, H] and dense w [in, out]: shard last dim
            return P(None, "tp")
        return P()

    return jax.tree_util.tree_map_with_path(rule, params)


def _path_keys(path: tuple) -> tuple:
    """Normalize a tree_util key path to a tuple of strings — DictKey
    carries .key, GetAttrKey .name, SequenceKey .idx."""
    return tuple(
        str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))
        for p in path
    )


def opt_state_pspec(opt_state: Any, params: Any, tp: int = 1, ep: int = 1) -> Any:
    """PartitionSpecs for an optax state tree: moment leaves (mu/nu/...)
    mirror their parameter and shard LIKE it; bookkeeping scalars
    (step counts, empty states) replicate.

    Matching is by trailing key path — optax nests the full param path
    under each stat field (``0/mu/<param path>``), so the longest
    suffix of an opt-state leaf path that names a param (with an equal
    shape) carries that param's spec. This is the train-side half of
    the golden contract: the serve-side shard_map specs were pinned in
    ISSUE 4, the optimizer state was "inferred by jit" — unpinned, so a
    resharding could ship silently. `make specs` now pins it
    (resources/specs/<model>_train.json, ALZ023)."""
    p_spec = param_pspec(params, tp=tp, ep=ep)
    param_table: dict[tuple, tuple] = {}
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_s = jax.tree_util.tree_flatten_with_path(p_spec)[0]
    for (path, leaf), (_, spec) in zip(flat_p, flat_s):
        param_table[_path_keys(path)] = (tuple(leaf.shape), spec)

    def rule(path: tuple, leaf) -> P:
        parts = _path_keys(path)
        for i in range(len(parts)):
            hit = param_table.get(parts[i:])
            if hit is not None and hit[0] == tuple(leaf.shape):
                return hit[1]
        return P()

    return jax.tree_util.tree_map_with_path(rule, opt_state)


def graph_pspec(stacked: bool = True) -> dict:
    """Graph-batch pytree spec: leading G axis sharded over 'dp'."""
    lead = ("dp",) if stacked else ()

    def spec(extra_dims: int) -> P:
        return P(*lead, *([None] * extra_dims))

    return {
        "node_feats": spec(2),
        "node_type": spec(1),
        "node_mask": spec(1),
        "node_deg": spec(1),
        "edge_src": spec(1),
        "edge_dst": spec(1),
        "edge_type": spec(1),
        "edge_feats": spec(2),
        "edge_mask": spec(1),
        # blocked layout only: per-128-dst-row extent table. Absent from
        # COO batches — consumers key off the data dict, so the extra
        # entry here is inert under the default layout.
        "edge_block_starts": spec(1),
    }


def stack_graphs(batches: list[GraphBatch]) -> tuple[dict, np.ndarray]:
    """Stack same-bucket GraphBatches into [G, ...] arrays + labels."""
    assert len({(b.n_pad, b.e_pad) for b in batches}) == 1, "mixed shape buckets"
    graphs = [b.device_arrays() for b in batches]
    stacked = {k: np.stack([g[k] for g in graphs]) for k in graphs[0]}
    labels = np.stack([b.edge_label for b in batches])
    return stacked, labels


# ---------------------------------------------------------------------------
# Sharded steps
# ---------------------------------------------------------------------------


def _ep_safe_cfg(cfg: ModelConfig, mesh: Mesh) -> ModelConfig:
    """Force the masked expert dispatch when the expert axis actually
    shards: the table form's [T, N, H] per-expert tables would all-gather
    across 'ep' every layer (ModelConfig.expert_dispatch docs)."""
    if mesh.shape.get("ep", 1) > 1 and cfg.expert_dispatch != "masked":
        from dataclasses import replace

        return replace(cfg, expert_dispatch="masked")
    return cfg


def make_sharded_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    params_example: Any,
    pos_weight: float = 10.0,
) -> Callable:
    """jit'd train step over a dp-sharded stack of graphs with tp-sharded
    params. Returns step(params, opt_state, stacked_graph, labels)."""
    cfg = _ep_safe_cfg(cfg, mesh)
    _, apply = get_model(cfg.model)
    p_spec = param_pspec(params_example, tp=mesh.shape.get("tp", 1), ep=mesh.shape.get("ep", 1))
    g_spec = graph_pspec(stacked=True)

    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), p_spec)
    # optimizer state placed EXPLICITLY, not left for jit to infer: the
    # moments must live where their params live or the first update
    # resheds the whole state (and the contract is pinned — ALZ023)
    opt_example = jax.eval_shape(optimizer.init, params_example)
    o_spec = opt_state_pspec(
        opt_example,
        params_example,
        tp=mesh.shape.get("tp", 1),
        ep=mesh.shape.get("ep", 1),
    )
    opt_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), o_spec)
    graph_sh = {k: NamedSharding(mesh, s) for k, s in g_spec.items()}
    label_sh = NamedSharding(mesh, P("dp", None))

    def loss_fn(params, stacked_graph, labels):
        def one(graph, lbl):
            out = apply(params, graph, cfg)
            return edge_bce_loss(
                out["edge_logits"], lbl, graph["edge_mask"].astype(jnp.float32), pos_weight
            )

        losses = jax.vmap(one)(stacked_graph, labels)
        return jnp.mean(losses)

    @jax.jit
    def step(params, opt_state, stacked_graph, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, stacked_graph, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def run(params, opt_state, stacked_graph_np, labels_np):
        params = jax.device_put(params, param_sh)
        opt_state = jax.device_put(opt_state, opt_sh)
        graph = {
            k: jax.device_put(jnp.asarray(v), graph_sh[k])
            for k, v in stacked_graph_np.items()
        }
        labels = jax.device_put(jnp.asarray(labels_np), label_sh)
        return step(params, opt_state, graph, labels)

    return run


def make_sharded_score_step(cfg: ModelConfig, mesh: Mesh, params_example: Any) -> Callable:
    """jit'd inference over a dp-sharded stack of graphs."""
    cfg = _ep_safe_cfg(cfg, mesh)
    _, apply = get_model(cfg.model)
    p_spec = param_pspec(params_example, tp=mesh.shape.get("tp", 1), ep=mesh.shape.get("ep", 1))
    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), p_spec)
    graph_sh = {k: NamedSharding(mesh, s) for k, s in graph_pspec(True).items()}

    @jax.jit
    def score(params, stacked_graph):
        return jax.vmap(lambda g: apply(params, g, cfg)["edge_logits"])(stacked_graph)

    def run(params, stacked_graph_np):
        params = jax.device_put(params, param_sh)
        graph = {
            k: jax.device_put(jnp.asarray(v), graph_sh[k])
            for k, v in stacked_graph_np.items()
        }
        return score(params, graph)

    return run
