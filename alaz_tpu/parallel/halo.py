"""Ring halo exchange: message passing over node-sharded graphs (SP/CP).

For graphs too big for one chip (BASELINE.json config 5: 100k-pod fleets),
the node axis is sharded across the ``sp`` mesh axis. Local edges (grouped
by destination shard) may have *remote* sources — the halo. Instead of
gathering all remote rows (memory blow-up), node-feature shards rotate
around the ring and each device folds in the messages whose source lives
in the block it currently holds — the graph analog of ring attention:
D steps, one neighbor ppermute per step, peak memory one block
(SURVEY §2.3 P4; blockwise aggregation caps memory like blockwise
attention).

Layout contract (prepared by ``shard_graph``):
- nodes are partitioned contiguously: shard d owns slots [d·n_loc, (d+1)·n_loc)
- each shard holds the edges whose **dst** is local, dst-sorted, padded to
  a common per-shard edge budget
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alaz_tpu.parallel.collectives import ring_shift


def ring_gather_scatter(
    h_local: jnp.ndarray,  # [n_loc, F] this shard's node states
    edge_src: jnp.ndarray,  # [e_loc] GLOBAL src ids of local-dst edges
    edge_dst_local: jnp.ndarray,  # [e_loc] LOCAL dst ids (dst - my_offset)
    edge_mask: jnp.ndarray,  # [e_loc]
    axis: str = "sp",
) -> jnp.ndarray:
    """out[d_local] = Σ_{e: dst=d} h[src[e]] with h sharded over ``axis``.

    Must run inside shard_map over ``axis``. D ring steps; at step k this
    device holds the block owned by (my_idx - k) mod D and processes the
    edges whose src falls in it.
    """
    n_loc = h_local.shape[0]
    d = jax.lax.axis_size(axis)
    my_idx = jax.lax.axis_index(axis)

    src_owner = edge_src // n_loc
    src_local = edge_src % n_loc

    def body(k, carry):
        acc, blk = carry
        owner = jax.lax.rem(my_idx - k + d, d)
        sel = (src_owner == owner) & edge_mask
        msgs = blk[src_local] * sel[:, None].astype(blk.dtype)
        acc = acc + jax.ops.segment_sum(msgs, edge_dst_local, num_segments=n_loc)
        blk = ring_shift(blk, axis, shift=1)
        return acc, blk

    acc0 = jnp.zeros_like(h_local)
    acc, _ = jax.lax.fori_loop(0, d, body, (acc0, h_local))
    return acc


def shard_graph(
    node_feats: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_shards: int,
):
    """Partition a COO graph for the halo layer.

    Returns per-shard stacked arrays (leading axis = shard):
    ``h [D, n_loc, F]``, ``src [D, e_loc]`` (global ids), ``dst_local
    [D, e_loc]``, ``mask [D, e_loc]``. Nodes must already be padded to a
    multiple of ``n_shards``; per-shard edge budget is the max shard edge
    count rounded up to 128."""
    n = node_feats.shape[0]
    assert n % n_shards == 0, "pad node count to a multiple of n_shards"
    n_loc = n // n_shards

    owner = edge_dst // n_loc
    e_budget = 0
    per_shard = []
    for s in range(n_shards):
        sel = owner == s
        per_shard.append((edge_src[sel], edge_dst[sel] - s * n_loc))
        e_budget = max(e_budget, int(sel.sum()))
    e_budget = max(128, ((e_budget + 127) // 128) * 128)

    h = node_feats.reshape(n_shards, n_loc, -1)
    src = np.zeros((n_shards, e_budget), dtype=np.int32)
    dst_local = np.full((n_shards, e_budget), n_loc - 1, dtype=np.int32)
    mask = np.zeros((n_shards, e_budget), dtype=bool)
    for s, (es, ed) in enumerate(per_shard):
        order = np.argsort(ed, kind="stable")
        k = es.shape[0]
        src[s, :k] = es[order]
        dst_local[s, :k] = ed[order]
        mask[s, :k] = True
    return h, src, dst_local, mask


def make_halo_aggregate(mesh: Mesh, axis: str = "sp"):
    """jit'd node-sharded aggregation: stacked shard arrays in, stacked
    per-shard sums out. The shard axis maps onto the mesh's ``axis``."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis),
    )
    def run(h, src, dst_local, mask):
        # shard_map passes blocks with the leading shard axis of size 1
        out = ring_gather_scatter(h[0], src[0], dst_local[0], mask[0], axis=axis)
        return out[None]

    return jax.jit(run)
