"""Ring halo exchange: message passing over node-sharded graphs (SP/CP).

For graphs too big for one chip (BASELINE.json config 5: 100k-pod fleets),
the node axis is sharded across the ``sp`` mesh axis. Local edges (grouped
by destination shard) may have *remote* sources — the halo. Instead of
gathering all remote rows (memory blow-up), node-feature shards rotate
around the ring and each device folds in the messages whose source lives
in the block it currently holds — the graph analog of ring attention:
D steps, one neighbor ppermute per step, peak memory one block
(SURVEY §2.3 P4; blockwise aggregation caps memory like blockwise
attention).

Layout contract (prepared by ``shard_graph``):
- nodes are partitioned contiguously: shard d owns slots [d·n_loc, (d+1)·n_loc)
- each shard holds the edges whose **dst** is local, dst-sorted, padded to
  a common per-shard edge budget
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from alaz_tpu.ops.segment import ATTENTION_LOGIT_CLAMP, blocked_segment_sum
from alaz_tpu.parallel.collectives import axis_size, ring_shift
from alaz_tpu.parallel.mesh import shard_map


def _hop_segment_sum(data, edge_dst_local, n_loc, block_starts):
    """The per-hop local reduce both ring aggregators share: the plain
    sorted segment sum under COO, the extent-aware tiled reduce when the
    blocked layout ships shard-local ``block_starts`` (ISSUE 20) —
    bit-exact either way, since every hop's messages are already
    sel-masked to zero on non-live edges."""
    if block_starts is not None:
        return blocked_segment_sum(data, edge_dst_local, block_starts, n_loc)
    return jax.ops.segment_sum(data, edge_dst_local, num_segments=n_loc)


def ring_gather_scatter(
    h_local: jnp.ndarray,  # [n_loc, F] this shard's node states
    edge_src: jnp.ndarray,  # [e_loc] GLOBAL src ids of local-dst edges
    edge_dst_local: jnp.ndarray,  # [e_loc] LOCAL dst ids (dst - my_offset)
    edge_mask: jnp.ndarray,  # [e_loc]
    axis: str = "sp",
    block_starts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """out[d_local] = Σ_{e: dst=d} h[src[e]] with h sharded over ``axis``.

    Must run inside shard_map over ``axis``. D ring steps; at step k this
    device holds the block owned by (my_idx - k) mod D and processes the
    edges whose src falls in it. ``block_starts`` (shard-local blocked
    extents, sharded_model.shard_block_starts) routes each hop's reduce
    through the blocked layout's tiled path.
    """
    n_loc = h_local.shape[0]
    d = axis_size(axis)
    my_idx = jax.lax.axis_index(axis)

    src_owner = edge_src // n_loc
    src_local = edge_src % n_loc

    def body(k, carry):
        acc, blk = carry
        owner = jax.lax.rem(my_idx - k + d, d)
        sel = (src_owner == owner) & edge_mask
        msgs = blk[src_local] * sel[:, None].astype(blk.dtype)
        acc = acc + _hop_segment_sum(msgs, edge_dst_local, n_loc, block_starts)
        blk = ring_shift(blk, axis, shift=1)
        return acc, blk

    acc0 = jnp.zeros_like(h_local)
    acc, _ = jax.lax.fori_loop(0, d, body, (acc0, h_local))
    return acc


def ring_gather_edges(
    h_local: jnp.ndarray,  # [n_loc, F] this shard's node states
    edge_src: jnp.ndarray,  # [e_loc] GLOBAL src ids of local-dst edges
    edge_mask: jnp.ndarray,  # [e_loc]
    axis: str = "sp",
) -> jnp.ndarray:
    """Per-edge ``h[src[e]]`` with h sharded over ``axis`` — the ring
    counterpart of a cross-shard gather: node blocks rotate and each
    device fills in the rows whose src lives in the block it currently
    holds (D steps, one ppermute per step, peak memory one block). Used
    by the node-sharded edge head, where every edge needs its (possibly
    remote) source state, not an aggregate."""
    n_loc = h_local.shape[0]
    d = axis_size(axis)
    my_idx = jax.lax.axis_index(axis)

    src_owner = edge_src // n_loc
    src_local = edge_src % n_loc

    def body(k, carry):
        out, blk = carry
        owner = jax.lax.rem(my_idx - k + d, d)
        sel = (src_owner == owner) & edge_mask
        out = jnp.where(sel[:, None], blk[src_local], out)
        blk = ring_shift(blk, axis, shift=1)
        return out, blk

    # derive the zero init from the sharded input so its varying-axes
    # annotation matches the loop body's output under shard_map
    out0 = h_local[src_local] * jnp.zeros((), h_local.dtype)
    out, _ = jax.lax.fori_loop(0, d, body, (out0, h_local))
    return out


def ring_attention_aggregate(
    q_part: jnp.ndarray,  # [n_loc, nh] dst-side logit partials (local)
    kv_local: jnp.ndarray,  # [n_loc, nh*hd] kv projections (the rotating block)
    e_part: jnp.ndarray,  # [e_loc, nh] edge-feature logit partials (local edges)
    e_feat: jnp.ndarray,  # [e_loc, nh, hd] edge-feature messages (local edges)
    a_k: jnp.ndarray,  # [nh, hd] src-side attention vector
    edge_src: jnp.ndarray,  # [e_loc] GLOBAL src ids of local-dst edges
    edge_dst_local: jnp.ndarray,  # [e_loc] LOCAL dst ids
    edge_mask: jnp.ndarray,  # [e_loc]
    axis: str = "sp",
    logit_clamp: float = ATTENTION_LOGIT_CLAMP,
    block_starts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """**Ring attention for graphs**: the fused GAT softmax-aggregate
    (models/gat.py layer_fn) over a node-sharded graph. Per ring hop this
    device holds one remote kv block and folds in the edges whose src
    lives there: logits = leaky_relu(q_part[dst] + a_k·kv[src] + e_part)
    clamped to ±logit_clamp, then the exp-weighted messages AND the exp
    column accumulate in one segment sum; the per-node division happens
    once after the ring. The fixed clamp is what removes classic ring
    attention's running-max recurrence — every hop's exp is already safe
    in f32, so numerator/denominator are plain ring-accumulated sums
    (SURVEY §2.3 P4; the blockwise-normalizer trick of blockwise/ring
    attention, degenerate because the max is a compile-time constant).

    Must run inside shard_map over ``axis``. Returns [n_loc, nh*hd]
    normalized attention aggregates for the local nodes.

    Src ownership is derived as ``edge_src // n_loc``: node shards MUST
    be uniform and contiguous (shard k owns global ids [k·n_loc,
    (k+1)·n_loc)), exactly what ``shard_graph_batch`` /
    ``shard_graph`` produce. A non-uniform layout would silently route
    edges to the wrong hop — repartition through those helpers first.
    """
    n_loc = kv_local.shape[0]
    nh, hd = a_k.shape
    out_dtype = kv_local.dtype
    d = axis_size(axis)
    my_idx = jax.lax.axis_index(axis)

    src_owner = edge_src // n_loc
    src_local = edge_src % n_loc
    # dst side is shard-local: one local gather, hoisted out of the ring.
    # Logits and both ring accumulators run f32 regardless of input
    # dtype — the same f32-denominator rule as segment_sum_accurate: a
    # bf16 running sum stagnates at hub fan-in ~256, and here the sum
    # also spans D hops.
    q_e = q_part[edge_dst_local].astype(jnp.float32)  # [e_loc, nh]
    e_part32 = e_part.astype(jnp.float32)
    a_k32 = a_k.astype(jnp.float32)

    def body(k, carry):
        acc, blk = carry
        owner = jax.lax.rem(my_idx - k + d, d)
        sel = (src_owner == owner) & edge_mask
        kv_src = blk[src_local].reshape(-1, nh, hd)
        k_src = jnp.einsum(
            "ehd,hd->eh", kv_src.astype(jnp.float32), a_k32
        )
        logits = jax.nn.leaky_relu(q_e + k_src + e_part32, 0.2)
        logits = jnp.clip(logits, -logit_clamp, logit_clamp)
        w = jnp.where(sel[:, None], jnp.exp(logits), 0.0)  # [e_loc, nh] f32
        msgs = (
            (kv_src + e_feat).astype(jnp.float32) * w[:, :, None]
        ).reshape(-1, nh * hd)
        fused = jnp.concatenate([msgs, w], axis=1)
        acc = acc + _hop_segment_sum(fused, edge_dst_local, n_loc, block_starts)
        blk = ring_shift(blk, axis, shift=1)
        return acc, blk

    # derive the zero init from the sharded inputs so its varying-axes
    # annotation matches the loop body's output under shard_map (same
    # trick as ring_gather_edges)
    acc0 = jnp.concatenate([kv_local, q_part], axis=1).astype(
        jnp.float32
    ) * jnp.zeros((), jnp.float32)
    acc, _ = jax.lax.fori_loop(0, d, body, (acc0, kv_local))
    num = acc[:, : nh * hd].reshape(n_loc, nh, hd)
    den = acc[:, nh * hd :]  # [n_loc, nh]
    nonempty = den > 0.0
    return (
        jnp.where(
            nonempty[:, :, None],
            num / jnp.where(nonempty, den, 1.0)[:, :, None],
            0.0,
        )
        .reshape(n_loc, nh * hd)
        .astype(out_dtype)
    )


def partition_edges_by_dst(
    edge_dst: np.ndarray,
    n_nodes: int,
    n_shards: int,
    edge_mask: np.ndarray | None = None,
) -> tuple[list[np.ndarray], int, int]:
    """The shared shard-layout core: contiguous node ownership, per-shard
    dst-sorted edge index lists, common 128-rounded edge budget. Returns
    (per-shard global edge indices in dst order, e_budget, n_loc). Both
    ``shard_graph`` and ``sharded_model.shard_graph_batch`` build on this
    so the ring kernels see one layout contract."""
    assert n_nodes % n_shards == 0, "pad node count to a multiple of n_shards"
    n_loc = n_nodes // n_shards
    owner = edge_dst // n_loc
    keep = np.ones(edge_dst.shape[0], bool) if edge_mask is None else edge_mask.astype(bool)
    per_shard = []
    e_budget = 0
    for s in range(n_shards):
        sel = np.flatnonzero((owner == s) & keep)
        sel = sel[np.argsort(edge_dst[sel], kind="stable")]
        per_shard.append(sel)
        e_budget = max(e_budget, sel.shape[0])
    e_budget = max(128, ((e_budget + 127) // 128) * 128)
    return per_shard, e_budget, n_loc


def shard_graph(
    node_feats: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_shards: int,
):
    """Partition a COO graph for the halo layer.

    Returns per-shard stacked arrays (leading axis = shard):
    ``h [D, n_loc, F]``, ``src [D, e_loc]`` (global ids), ``dst_local
    [D, e_loc]``, ``mask [D, e_loc]``. Nodes must already be padded to a
    multiple of ``n_shards``; per-shard edge budget is the max shard edge
    count rounded up to 128."""
    n = node_feats.shape[0]
    per_shard, e_budget, n_loc = partition_edges_by_dst(edge_dst, n, n_shards)

    h = node_feats.reshape(n_shards, n_loc, -1)
    src = np.zeros((n_shards, e_budget), dtype=np.int32)
    dst_local = np.full((n_shards, e_budget), n_loc - 1, dtype=np.int32)
    mask = np.zeros((n_shards, e_budget), dtype=bool)
    for s, idx in enumerate(per_shard):
        k = idx.shape[0]
        src[s, :k] = edge_src[idx]
        dst_local[s, :k] = edge_dst[idx] - s * n_loc
        mask[s, :k] = True
    return h, src, dst_local, mask


def make_halo_aggregate(mesh: Mesh, axis: str = "sp"):
    """jit'd node-sharded aggregation: stacked shard arrays in, stacked
    per-shard sums out. The shard axis maps onto the mesh's ``axis``."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis),
    )
    def run(h, src, dst_local, mask):
        # shard_map passes blocks with the leading shard axis of size 1
        out = ring_gather_scatter(h[0], src[0], dst_local[0], mask[0], axis=axis)
        return out[None]

    return jax.jit(run)
