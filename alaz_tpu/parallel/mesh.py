"""Mesh construction.

Axes (SURVEY §2.3): ``dp`` data-parallel over window batches, ``tp``
tensor-parallel over hidden dims, ``ep`` expert-parallel over edge-type
experts, ``sp`` sequence/temporal-parallel over node shards (halo layer).
All four axes always exist (size 1 collapses harmlessly), so
PartitionSpecs are stable across topologies. On multi-host TPU, the
device order from ``jax.devices()`` keeps ICI-adjacent chips adjacent on
the trailing axes; put ``dp`` on the outermost (DCN-crossing) axis.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

from alaz_tpu.config import MeshConfig

# jax.shard_map graduated out of jax.experimental between jax releases
# (and renamed its check_rep knob to check_vma on the way); resolve
# whichever this jax exposes so the whole parallel layer (and the tests)
# works on both sides of the move.
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, **kwargs)

AXES = ("dp", "tp", "ep", "sp")


def mesh_shape_for(n_devices: int, tp: int = 1, ep: int = 1, sp: int = 1) -> MeshConfig:
    """Fill dp with whatever the other axes leave over."""
    rest = tp * ep * sp
    assert n_devices % rest == 0, f"{n_devices} devices not divisible by tp*ep*sp={rest}"
    return MeshConfig(dp=n_devices // rest, tp=tp, ep=ep, sp=sp)


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg is None:
        cfg = mesh_shape_for(n)
    shape = (cfg.dp, cfg.tp, cfg.ep, cfg.sp)
    assert int(np.prod(shape)) == n, f"mesh {shape} != {n} devices"
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)
