"""Multi-host / multi-slice meshes (DCN across slices, ICI within).

The 100k-pod config (BASELINE.json config 5) spans a v5e-64: multiple
hosts, possibly multiple slices. ``initialize_distributed`` wraps
``jax.distributed.initialize`` (coordinator discovery via env/args), and
``make_hybrid_mesh`` builds a mesh whose *outermost* axis crosses the DCN
boundary (slices) while the inner axes stay on ICI — so dp gradients ride
DCN once per step and tp/sp collectives stay intra-slice, the layout the
scaling-book recipe prescribes.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh

from alaz_tpu.config import MeshConfig
from alaz_tpu.logging import get_logger
from alaz_tpu.parallel.mesh import AXES

log = get_logger("alaz_tpu.multislice")


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with env fallbacks
    (ALAZ_TPU_COORDINATOR / JAX_COORDINATOR_ADDRESS etc.). No-op when
    single-process."""
    coordinator_address = coordinator_address or os.environ.get(
        "ALAZ_TPU_COORDINATOR", os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    if coordinator_address is None and num_processes is None:
        return  # single-process: nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        f"distributed initialized: process {jax.process_index()}/{jax.process_count()}"
    )


def make_hybrid_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    """Mesh over all (global) devices with dp outermost.

    Device order: JAX returns devices grouped by process/slice, so
    reshaping (dp, tp, ep, sp) with dp first puts the slice boundary on
    the dp axis — dp collectives cross DCN, the rest stay on ICI. When
    dp doesn't divide evenly into slices the mesh still works; the
    placement is just less DCN-optimal.
    """
    if devices is None:
        devices = jax.devices()  # global across processes
    n = len(devices)
    shape = (cfg.dp, cfg.tp, cfg.ep, cfg.sp)
    assert int(np.prod(shape)) == n, f"mesh {shape} != {n} devices"
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def slice_count(devices=None) -> int:
    """Number of distinct slices among the devices (1 on single-slice)."""
    if devices is None:
        devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    return len(slice_ids)
