"""Node-sharded model forwards (GraphSAGE + GAT) — the config-5 serving
path (BASELINE.json: 100k-pod multi-cluster graphs sharded across a
slice).

For graphs too big for one chip, the node axis is partitioned over the
``sp`` mesh axis and the whole forward runs inside one shard_map:
message aggregation crosses shards via the ring halo exchange
(halo.ring_gather_scatter — the graph analog of ring attention), the
edge head's remote source states arrive via the per-edge ring gather
(halo.ring_gather_edges), and everything else is shard-local dense math.
SURVEY §7 hard part (d): cross-shard neighbor halos without blowing ICI
latency — D ppermute hops per layer, peak extra memory one node block.

Numerically equivalent to the single-device ``graphsage.apply`` (same
params): validated edge-for-edge in tests/test_parallel.py via the
permutation ``shard_graph_batch`` returns.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from alaz_tpu.config import ModelConfig
from alaz_tpu.graph.snapshot import EDGE_BLOCK_ROWS, GraphBatch
from alaz_tpu.models.common import (
    compute_dtype,
    dense,
    layernorm,
    masked_degree,
    mlp,
    scatter_messages,
    znorm_edge_feats,
)
from alaz_tpu.parallel.mesh import shard_map
from alaz_tpu.parallel.halo import (
    partition_edges_by_dst,
    ring_attention_aggregate,
    ring_gather_edges,
    ring_gather_scatter,
)


# The shard-local array set a node-sharded forward consumes (what
# shard_graph_batch emits), in wire order.
SHARDED_GRAPH_KEYS = (
    "node_feats",
    "node_type",
    "node_mask",
    "edge_src",
    "edge_dst_local",
    "edge_type",
    "edge_feats",
    "edge_mask",
)


def node_sharded_specs(axis: str = "sp") -> tuple[tuple, tuple]:
    """The shard_map (in_specs, out_specs) contract BOTH node-sharded
    makers compile against: params replicated, every graph array sharded
    on its leading S axis, both logit outputs sharded the same way.
    Exported as a function so alazspec pins it in the golden specfiles
    (ALZ023) — an in_spec edited in one maker but not the contract fails
    tier-1 instead of silently re-sharding the batch."""
    in_specs = (P(), {k: P(axis) for k in SHARDED_GRAPH_KEYS})
    out_specs = (P(axis), P(axis))
    return in_specs, out_specs


def shard_graph_batch(batch: GraphBatch, n_shards: int) -> tuple[dict, np.ndarray]:
    """Partition one GraphBatch for the node-sharded forward.

    Nodes split contiguously (n_pad must divide by n_shards — bucket
    sizes are powers of two, so any pow2 shard count works); each shard
    receives the edges whose dst is local, dst-sorted, padded to a common
    per-shard budget. Returns (stacked shard arrays, perm) where
    ``perm[s, i]`` is the global edge index in slot i of shard s (-1 =
    padding) so callers can scatter per-edge outputs back to batch order.
    """
    n, e = batch.n_pad, batch.e_pad
    per_shard, e_budget, n_loc = partition_edges_by_dst(
        batch.edge_dst, n, n_shards, edge_mask=batch.edge_mask
    )

    def alloc(shape, dtype, fill=0):
        return np.full(shape, fill, dtype=dtype)

    out = {
        "node_feats": batch.node_feats.reshape(n_shards, n_loc, -1),
        "node_type": batch.node_type.reshape(n_shards, n_loc),
        "node_mask": batch.node_mask.reshape(n_shards, n_loc),
        "edge_src": alloc((n_shards, e_budget), np.int32),
        "edge_dst_local": alloc((n_shards, e_budget), np.int32, n_loc - 1),
        "edge_type": alloc((n_shards, e_budget), np.int32),
        "edge_feats": alloc(
            (n_shards, e_budget, batch.edge_feats.shape[1]), np.float32
        ),
        "edge_mask": alloc((n_shards, e_budget), bool),
    }
    perm = np.full((n_shards, e_budget), -1, dtype=np.int64)
    for s, idx in enumerate(per_shard):  # already dst-sorted by the core
        k = idx.shape[0]
        out["edge_src"][s, :k] = batch.edge_src[idx]
        out["edge_dst_local"][s, :k] = batch.edge_dst[idx] - s * n_loc
        out["edge_type"][s, :k] = batch.edge_type[idx]
        out["edge_feats"][s, :k] = batch.edge_feats[idx]
        out["edge_mask"][s, :k] = True
        perm[s, :k] = idx
    return out, perm


def shard_block_starts(
    dst_local: jnp.ndarray, edge_mask: jnp.ndarray, n_loc: int
) -> jnp.ndarray | None:
    """Shard-local twin of graph/snapshot.edge_block_starts_from: the
    per-128-dst-row extents over this shard's live edge prefix, derived
    in-body (the host wire format — SHARDED_GRAPH_KEYS — is unchanged).

    Valid because ``edge_dst_local`` is globally sorted: the live prefix
    is dst-sorted by partition_edges_by_dst and the pad fill (n_loc - 1)
    is >= every live value. Interior extents from searchsorted therefore
    agree with the host definition; only the final sentinel would land
    at e_budget instead of n_live (pads share dst n_loc - 1), so the
    minimum clamps every entry to the live-edge frontier. Requires
    n_loc % EDGE_BLOCK_ROWS == 0 — callers gate (n_loc can be 64 at the
    smallest bucket over 4 shards; e_budget is always 128-rounded)."""
    if n_loc % EDGE_BLOCK_ROWS != 0:
        return None
    n_live = jnp.sum(edge_mask.astype(jnp.int32))
    bounds = jnp.arange(0, n_loc + 1, EDGE_BLOCK_ROWS, dtype=jnp.int32)
    starts = jnp.searchsorted(dst_local.astype(jnp.int32), bounds)
    return jnp.minimum(starts.astype(jnp.int32), n_live)


def _sharded_heads(params, h, ef, src, dst_local, edge_mask, dtype, axis):
    """The split edge head + node head over one node shard (shared by
    both node-sharded forwards so the serving paths cannot drift):
    models/common.edge_head's re-association, with the remote src states
    arriving via the per-edge ring gather."""
    w1 = params["edge_head"][0]["w"].astype(dtype)
    hdim = h.shape[-1]
    u = h @ w1[:hdim]
    v = h @ w1[hdim : 2 * hdim]
    u_e = ring_gather_edges(u.astype(jnp.float32), src, edge_mask, axis=axis)
    z = (
        u_e.astype(dtype)
        + v[dst_local]
        + ef @ w1[2 * hdim :]
        + params["edge_head"][0]["b"].astype(dtype)
    )
    edge_logits = mlp(params["edge_head"][1:], jax.nn.gelu(z))[:, 0]
    node_logits = mlp(params["node_head"], h)[:, 0]
    return (
        edge_logits.astype(jnp.float32)[None],
        node_logits.astype(jnp.float32)[None],
    )



def _maybe_znorm_sharded(ef_raw, edge_mask, cfg, axis: str, dtype):
    """Shard-side twin of models/common.py maybe_znorm_graph: the
    fleet-baseline z-stats are a GLOBAL per-window reduction, psum'd over
    the node shards so sharded forwards match the single-device apply
    bit-for-tolerance (parity tests)."""
    if cfg.edge_feat_znorm and ef_raw.shape[1] < cfg.edge_feat_dim_in:
        ef_raw = znorm_edge_feats(ef_raw, edge_mask, axis=axis)
    return ef_raw.astype(dtype)


def make_node_sharded_graphsage(
    cfg: ModelConfig, mesh: Mesh, axis: str = "sp"
) -> Callable:
    """jit'd node-sharded forward: (params, sharded arrays) →
    (edge_logits [S, e_budget], node_logits [S, n_loc]). Params are
    replicated over ``axis``; node/edge arrays are sharded on their
    leading S axis."""

    in_specs, out_specs = node_sharded_specs(axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        # jax 0.4.37's shard_map replication checker rejects the ring
        # fori_loop's carry under reverse-mode AD ("Scan carry input and
        # output got mismatched replication types") — the documented
        # workaround until the fixed checker (check_vma) lands; layout
        # correctness is still covered edge-for-edge by the parity tests
        check_vma=False,
    )
    def run(params, g):
        dtype = compute_dtype(cfg)
        node_mask = g["node_mask"][0].astype(jnp.float32)
        edge_mask = g["edge_mask"][0]
        src, dst_local = g["edge_src"][0], g["edge_dst_local"][0]
        ef = _maybe_znorm_sharded(g["edge_feats"][0], edge_mask, cfg, axis, dtype)
        n_loc = g["node_feats"].shape[1]

        # f32 residual stream, mirroring the single-device forward
        # (models/graphsage.py): matmuls in the compute dtype, carry and
        # LN/GELU in f32 so bf16 sharded serving stays parity-exact
        h = dense(params["embed"], g["node_feats"][0].astype(dtype))
        h = h.astype(jnp.float32) * node_mask[:, None]

        # degree is layer-invariant: one [E] scatter per forward (the
        # same hoist the single-device models carry)
        deg = masked_degree(edge_mask, dst_local, n_loc, jnp.float32)
        # blocked layout: shard-local extents, derived once per forward
        # (layer-invariant like deg); static cfg branch = zero retraces
        bs = (
            shard_block_starts(dst_local, edge_mask, n_loc)
            if cfg.edge_layout == "blocked"
            else None
        )

        for layer in params["layers"]:
            # remote part: Σ_{dst local} (h W_msg)[src] via the ring
            hw = dense(layer["msg"], h.astype(dtype))
            ring_agg = ring_gather_scatter(
                hw.astype(jnp.float32), src, dst_local, edge_mask, axis=axis,
                block_starts=bs,
            )
            # local part: edge-feature messages scatter shard-locally,
            # through the Pallas kernel when the shard shapes qualify
            # (edges are 128-padded by construction; node blocks need the
            # kernel's TILE_N alignment)
            ef_msgs = dense(layer["edge_proj"], ef).astype(jnp.float32)
            ef_agg, _ = scatter_messages(
                ef_msgs, dst_local, edge_mask, n_loc,
                cfg.use_pallas if n_loc % 128 == 0 else False,
                deg=deg, block_starts=bs,
            )
            agg = (ring_agg + ef_agg) / jnp.maximum(deg, 1.0)[:, None]
            h_new = dense(layer["self"], h.astype(dtype)) + dense(
                layer["neigh"], agg.astype(dtype)
            )
            h_new = jax.nn.gelu(layernorm(layer["ln"], h_new.astype(jnp.float32)))
            h = (h + h_new) * node_mask[:, None]

        return _sharded_heads(
            params, h.astype(dtype), ef, src, dst_local, edge_mask, dtype, axis
        )

    return jax.jit(run)


def make_node_sharded_gat(
    cfg: ModelConfig, mesh: Mesh, axis: str = "sp"
) -> Callable:
    """jit'd node-sharded GAT forward (BASELINE config 3 at fleet
    scale): same signature as ``make_node_sharded_graphsage``. Attention
    crosses shards via ``halo.ring_attention_aggregate`` — the fused
    softmax-aggregate accumulates numerator and denominator over the
    ring hops, so cross-shard normalization needs no extra collective
    beyond the same D ppermutes the sum aggregation pays. Numerically
    equivalent to the single-device ``gat.apply`` (same params);
    validated edge-for-edge in tests/test_parallel.py."""
    nh = cfg.num_heads
    hd = cfg.hidden_dim // nh

    in_specs, out_specs = node_sharded_specs(axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        # same jax-0.4.37 replication-checker workaround as the
        # graphsage maker above (ring fori_loop carry under grad)
        check_vma=False,
    )
    def run(params, g):
        dtype = compute_dtype(cfg)
        node_mask = g["node_mask"][0].astype(jnp.float32)
        edge_mask = g["edge_mask"][0]
        src, dst_local = g["edge_src"][0], g["edge_dst_local"][0]
        ef = _maybe_znorm_sharded(g["edge_feats"][0], edge_mask, cfg, axis, dtype)
        n_loc = g["node_feats"].shape[1]

        # f32 residual stream, mirroring models/gat.py
        h = dense(params["embed"], g["node_feats"][0].astype(dtype))
        h = h.astype(jnp.float32) * node_mask[:, None]

        # blocked layout: shard-local extents (see the graphsage maker)
        bs = (
            shard_block_starts(dst_local, edge_mask, n_loc)
            if cfg.edge_layout == "blocked"
            else None
        )

        for layer in params["layers"]:
            attn = layer["attn"].astype(dtype)  # [nh, 3hd]
            a_q, a_k, a_e = attn[:, :hd], attn[:, hd : 2 * hd], attn[:, 2 * hd :]
            hc = h.astype(dtype)
            q = dense(layer["q"], hc).reshape(n_loc, nh, hd)
            kv = dense(layer["kv"], hc)  # [n_loc, nh*hd] — the ring block
            e_feat = dense(layer["edge_proj"], ef).reshape(-1, nh, hd)
            q_part = jnp.einsum("nhd,hd->nh", q, a_q)  # [n_loc, nh]
            e_part = jnp.einsum("ehd,hd->eh", e_feat, a_e)  # [e_loc, nh]
            agg = ring_attention_aggregate(
                q_part, kv, e_part, e_feat, a_k,
                src, dst_local, edge_mask, axis=axis, block_starts=bs,
            )
            h_new = dense(layer["out"], agg.astype(dtype))
            h = (
                h + jax.nn.gelu(layernorm(layer["ln"], h_new.astype(jnp.float32)))
            ) * node_mask[:, None]

        return _sharded_heads(
            params, h.astype(dtype), ef, src, dst_local, edge_mask, dtype, axis
        )

    return jax.jit(run)


def unshard_edge_outputs(
    sharded: Any, perm: np.ndarray, n_edges: int
) -> np.ndarray:
    """[S, e_budget] per-edge outputs → batch edge order using the perm
    from shard_graph_batch (padding slots dropped)."""
    flat = np.asarray(sharded).reshape(-1)
    perm_flat = perm.reshape(-1)
    out = np.zeros(n_edges, flat.dtype)
    valid = perm_flat >= 0
    out[perm_flat[valid]] = flat[valid]
    return out
