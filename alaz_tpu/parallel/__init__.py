"""Device-mesh parallelism (SURVEY §2.3).

- ``mesh``      — Mesh construction over (dp, tp, ep, sp) axes
- ``sharding``  — PartitionSpec rules for params and graph batches; the
                  sharded train/score steps (DP over windows, TP over
                  hidden dims; XLA inserts the collectives)
- ``halo``      — ring halo exchange for node-sharded graphs (SP/CP)
- ``gpipe``     — GPipe microbatch pipeline via ppermute hops (PP)
- ``sharded_model`` — node-sharded GraphSAGE forward (config-5 serving)
"""

from alaz_tpu.parallel.gpipe import make_pipeline
from alaz_tpu.parallel.mesh import make_mesh, mesh_shape_for
from alaz_tpu.parallel.sharding import (
    graph_pspec,
    make_sharded_train_step,
    param_pspec,
    stack_graphs,
)

__all__ = [
    "make_pipeline",
    "make_mesh",
    "mesh_shape_for",
    "graph_pspec",
    "param_pspec",
    "stack_graphs",
    "make_sharded_train_step",
]
