"""Environment-driven configuration.

The reference configures everything through environment variables read in
``main.go:28-188`` and tiny structs in ``config/`` (SURVEY §2.2 G1/G24).
We keep that contract — every knob has an ``ALAZ_TPU_*`` env var — but
centralize it in typed dataclasses so programmatic use (tests, replay
configs) doesn't go through the environment at all.

Simulation configs are JSON files with the same keys as the reference's
``testconfig/config1.json`` (camelCase accepted verbatim).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Mapping

_PREFIX = "ALAZ_TPU_"


def lookup_env(name: str, default: str | None = None, env=None) -> str | None:
    """The prefix-aware lookup (ALAZ_TPU_NAME wins over NAME) against an
    arbitrary mapping — for modules that take an injectable env."""
    if env is None:
        env = os.environ
    return env.get(_PREFIX + name, env.get(name, default))


def _env(name: str, default: str | None = None) -> str | None:
    return lookup_env(name, default)


def parse_bool(v: str | None, default: bool = False) -> bool:
    """One accepted-token set for every boolean knob. An unrecognized
    token keeps the DEFAULT rather than reading as False — a typo in a
    default-True security knob (LOG_BACKEND_TLS) must not silently
    disable it."""
    if v is None:
        return default
    t = v.strip().lower()
    if t in ("1", "true", "yes", "on"):
        return True
    if t in ("0", "false", "no", "off"):
        return False
    return default


def env_bool(name: str, default: bool = False) -> bool:
    return parse_bool(_env(name), default)


def env_int(name: str, default: int) -> int:
    v = _env(name)
    return default if v is None else int(v)


def env_float(name: str, default: float) -> float:
    v = _env(name)
    return default if v is None else float(v)


def env_str(name: str, default: str) -> str:
    v = _env(name)
    return default if v is None else v


@dataclass
class QueueConfig:
    """Bounded-queue capacities, mirroring the reference's channel sizes
    (ebpf/collector.go:79-81, main.go:82-90): drop-not-block at the source
    boundary, exactly like l7.go:764-770."""

    l7_events: int = 200_000
    tcp_events: int = 100_000
    proc_events: int = 20_000
    kube_events: int = 1_000
    ds_requests: int = 40_000
    ds_connections: int = 1_000
    ds_kafka: int = 2_000

    @classmethod
    def from_env(cls) -> "QueueConfig":
        return cls(
            l7_events=env_int("EVENTS_BUFFER_SIZE", 200_000),
            tcp_events=env_int("EBPF_TCP_EVENTS_BUFFER_SIZE", 100_000),
            proc_events=env_int("EBPF_PROC_EVENTS_BUFFER_SIZE", 20_000),
            kube_events=env_int("KUBE_EVENTS_BUFFER_SIZE", 1_000),
            ds_requests=env_int("DS_REQ_BUFFER_SIZE", 40_000),
            ds_connections=env_int("DS_CONN_BUFFER_SIZE", 1_000),
            ds_kafka=env_int("DS_KAFKA_BUFFER_SIZE", 2_000),
        )


@dataclass
class BackendConfig:
    """Batching/export cadence of the datastore backend
    (datastore/backend.go:280-338,591-765 and the HTTP client 210-278)."""

    host: str = ""
    monitoring_id: str = "test"
    node_id: str = "node-0"
    batch_size: int = 1_000
    req_flush_interval_s: float = 5.0
    conn_flush_interval_s: float = 30.0
    conn_batch_size: int = 500
    kafka_flush_interval_s: float = 5.0
    kafka_batch_size: int = 500
    resource_flush_interval_s: float = 5.0
    max_retries: int = 2
    backoff_min_s: float = 1.0
    backoff_max_s: float = 5.0
    timeout_s: float = 10.0
    metrics_export: bool = False
    metrics_export_interval_s: float = 10.0
    # circuit breaker on the send path (datastore/backend.py, ISSUE 6):
    # this many CONSECUTIVE failed sends (retry ladders included) open
    # the circuit; sends then shed instantly until a cooldown-gated
    # half-open probe succeeds. Sized so one flaky batch never trips it
    # but a down backend trips within seconds at default cadence.
    breaker_threshold: int = 5
    breaker_cooldown_s: float = 30.0

    @classmethod
    def from_env(cls) -> "BackendConfig":
        return cls(
            host=env_str("BACKEND_HOST", ""),
            monitoring_id=env_str("MONITORING_ID", "test"),
            node_id=env_str("NODE_NAME", "node-0"),
            batch_size=env_int("BATCH_SIZE", 1_000),
            metrics_export=env_bool("METRICS_ENABLED", False),
            breaker_threshold=env_int("BREAKER_THRESHOLD", 5),
            breaker_cooldown_s=env_float("BREAKER_COOLDOWN_S", 30.0),
        )


@dataclass
class SimulationConfig:
    """Replay-harness knobs; JSON-compatible with testconfig/config1.json
    (main_benchmark_test.go:40-80)."""

    test_duration_s: float = 15.0
    mem_prof_interval_s: float = 5.0
    pod_count: int = 100
    service_count: int = 50
    edge_count: int = 20
    edge_rate: int = 10_000  # events/sec/edge
    chunk_size: int = 8_192  # events per columnar batch emitted by the simulator
    seed: int = 0
    protocol_mix: Mapping[str, float] = field(default_factory=lambda: {"HTTP": 1.0})
    ds_req_buffer_size: int = 150_000
    mock_backend_min_latency_ms: float = 5.0
    mock_backend_max_latency_ms: float = 20.0

    @classmethod
    def from_json(cls, path_or_dict: str | Mapping[str, Any]) -> "SimulationConfig":
        if isinstance(path_or_dict, (str, os.PathLike)):
            with open(path_or_dict) as f:
                raw = json.load(f)
        else:
            raw = dict(path_or_dict)
        camel = {
            "testDuration": "test_duration_s",
            "memProfInterval": "mem_prof_interval_s",
            "podCount": "pod_count",
            "serviceCount": "service_count",
            "edgeCount": "edge_count",
            "edgeRate": "edge_rate",
            "dsReqBufferSize": "ds_req_buffer_size",
            "mockBackendMinLatency": "mock_backend_min_latency_ms",
            "mockBackendMaxLatency": "mock_backend_max_latency_ms",
            "chunkSize": "chunk_size",
            "seed": "seed",
            "protocolMix": "protocol_mix",
        }
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs: dict[str, Any] = {}
        for k, v in raw.items():
            key = camel.get(k, k)
            if key in known:
                kwargs[key] = v
        return cls(**kwargs)


@dataclass
class ChaosConfig:
    """Deterministic fault-injection intensities (alaz_tpu/chaos).

    OFF by default — ``enabled`` gates the whole plane; the chaos
    harness / ``bench.py --ingest --chaos <seed>`` / ``make chaos`` flip
    it on with a seed. The default intensities are the "default
    intensity" the acceptance gates run at: every seam active, faults
    frequent enough to exercise each degradation path in a short run,
    rare enough that detection quality must survive them."""

    enabled: bool = False
    seed: int = 0
    # frame seam (sources/ingest_server.py)
    frame_corrupt_prob: float = 0.02  # header magic garbled → resync
    frame_truncate_prob: float = 0.0  # payload cut short → resync
    frame_garble_prob: float = 0.02  # count field off → quarantine
    # delivery seam (batches between source and ingestion surface)
    batch_dup_prob: float = 0.05
    batch_reorder_prob: float = 0.05
    batch_late_prob: float = 0.03
    # worker seam (aggregator/sharded.py shard threads)
    worker_crash_prob: float = 0.01
    worker_stall_prob: float = 0.02
    worker_stall_s: float = 0.02
    worker_max_crashes: int = 4
    # backend seam (datastore/backend.py transport)
    backend_error_prob: float = 0.3
    backend_timeout_prob: float = 0.1

    @classmethod
    def from_env(cls) -> "ChaosConfig":
        return cls(
            enabled=env_bool("CHAOS_ENABLED", False),
            seed=env_int("CHAOS_SEED", 0),
            frame_corrupt_prob=env_float("CHAOS_FRAME_CORRUPT_PROB", 0.02),
            frame_truncate_prob=env_float("CHAOS_FRAME_TRUNCATE_PROB", 0.0),
            frame_garble_prob=env_float("CHAOS_FRAME_GARBLE_PROB", 0.02),
            batch_dup_prob=env_float("CHAOS_BATCH_DUP_PROB", 0.05),
            batch_reorder_prob=env_float("CHAOS_BATCH_REORDER_PROB", 0.05),
            batch_late_prob=env_float("CHAOS_BATCH_LATE_PROB", 0.03),
            worker_crash_prob=env_float("CHAOS_WORKER_CRASH_PROB", 0.01),
            worker_stall_prob=env_float("CHAOS_WORKER_STALL_PROB", 0.02),
            worker_stall_s=env_float("CHAOS_WORKER_STALL_S", 0.02),
            worker_max_crashes=env_int("CHAOS_WORKER_MAX_CRASHES", 4),
            backend_error_prob=env_float("CHAOS_BACKEND_ERROR_PROB", 0.3),
            backend_timeout_prob=env_float("CHAOS_BACKEND_TIMEOUT_PROB", 0.1),
        )


@dataclass
class TraceConfig:
    """Window-lifecycle span plane + flight recorder (ISSUE 9,
    alaz_tpu/obs). Tracing is ON by default — the measured cost is per
    window×stage, bounded ≤2% rows/s on the 1M-row ingest bench (the
    ``trace_overhead_pct`` A/B re-measures it every round)."""

    enabled: bool = True
    # live-span map bound: windows that never complete (scoring disabled
    # mid-run, shed window queue) evict LRU with a counter, never leak
    max_live: int = 4096
    # flight-recorder ring size (structured events, not rows)
    recorder_capacity: int = 512
    # dump the recorder tail to the log when a shard worker dies
    recorder_dump_on_crash: bool = True
    # device-side telemetry plane (ISSUE 11, obs/device.py): per-bucket
    # score latency + occupancy histograms, the stage arena/transfer
    # decomposition with its byte ledger, pad-waste accounting, and the
    # always-on compile event hookup. ON by default — cost is per
    # window×dispatch, inside the same ≤2% bench bound as the spans.
    device_enabled: bool = True
    # /profile endpoint bound (runtime/debug_http.py): a requested trace
    # longer than this is clamped — the endpoint must never wedge a
    # debug-port thread (or fill a disk) for an unbounded stretch
    profile_max_s: float = 30.0
    # score-plane observability (ISSUE 13, obs/scores.py): per-model
    # score-distribution sketches, PSI/L∞ drift detection with
    # churn-triggered rebaselining, and the top-K attribution ledger
    # (/scores, /scores/top). ON by default — cost is one vectorized
    # pass per scored window, inside the ≤2% bench bound
    # (score_plane_overhead_pct re-measures it every round).
    score_enabled: bool = True
    # rolling drift reference: the trailing K windows the current
    # window's score distribution is compared against (PSI + L∞-on-CDF
    # with hysteresis). Size to several multiples of the deploy cadence
    # you want paged on; a rebaseline refills it before judging resumes.
    score_drift_windows: int = 8
    # attribution ledger width: the K highest-scoring nodes kept per
    # window with feature z-scores + top contributing in-edges —
    # bounded cardinality by construction, never a per-node series
    score_top_k: int = 10

    @classmethod
    def from_env(cls) -> "TraceConfig":
        return cls(
            enabled=env_bool("TRACE_ENABLED", True),
            max_live=env_int("TRACE_MAX_LIVE", 4096),
            recorder_capacity=env_int("RECORDER_CAPACITY", 512),
            recorder_dump_on_crash=env_bool("RECORDER_DUMP_ON_CRASH", True),
            device_enabled=env_bool("DEVICE_TRACE_ENABLED", True),
            profile_max_s=env_float("PROFILE_MAX_SECONDS", 30.0),
            score_enabled=env_bool("SCORE_TRACE_ENABLED", True),
            score_drift_windows=env_int("SCORE_DRIFT_WINDOWS", 8),
            score_top_k=env_int("SCORE_TOP_K", 10),
        )


@dataclass
class ScenarioConfig:
    """Incident-scenario suite knobs (alaz_tpu/replay/incidents.py).

    The scenario library itself is parameterized per call; these are the
    defaults the suite drivers (``make scenarios``, ``bench.py
    --scenario`` and the ``--ingest`` ride-along) read, so a deployment
    can re-scale the fixed-seed sweep without touching code."""

    seed: int = 0
    n_workers: int = 2
    # hot_key stress fan-in (the acceptance bound); gate-scale runs use
    # the per-scenario defaults in incidents.py
    hot_key_fanin: int = 500_000
    # degree cap the hot_key scenario survives under (0 would disable
    # the defense and let the fan-in through — never the suite default)
    degree_cap: int = 1_024

    @classmethod
    def from_env(cls) -> "ScenarioConfig":
        return cls(
            seed=env_int("SCENARIO_SEED", 0),
            n_workers=env_int("SCENARIO_WORKERS", 2),
            hot_key_fanin=env_int("SCENARIO_HOT_KEY_FANIN", 500_000),
            degree_cap=env_int("SCENARIO_DEGREE_CAP", 1_024),
        )


@dataclass(frozen=True)
class ModelConfig:
    """Flagship model hyperparameters (BASELINE.json configs 2-4)."""

    model: str = "graphsage"  # graphsage | gat | tgn
    hidden_dim: int = 128
    num_layers: int = 2
    num_heads: int = 4  # gat only
    num_edge_types: int = 9  # one per L7 protocol enum slot
    # experts model routing form: "table" computes per-expert node tables
    # (T cheap N-row matmuls) then ONE (type,src) row gather — the
    # single-chip fast path (kills the T·E·H mask traffic of the masked
    # sum); "masked" is the Σ_t 1[type=t]·(h_src@W_t) form whose T axis
    # shards cleanly over the ep mesh axis (the sharded train/score steps
    # force it when ep>1)
    expert_dispatch: str = "table"
    node_feature_dim: int = 32
    edge_feature_dim: int = 16
    # append per-window z-scored copies of the leading stat columns
    # (count/latency/error rates) to the edge features inside the model:
    # each edge seen RELATIVE to the window's fleet baseline. Absolute
    # log-latency shifts of a ramping-but-not-yet-spiking edge are ~1e-2
    # of the feature scale (invisible next to node-embedding variance);
    # the z-scored copy puts the same drift tens of σ out — the input
    # representation that makes next-window forecasting learnable
    # (replay/scenario.py run_forecast_scenario).
    edge_feat_znorm: bool = True
    dropout: float = 0.1
    dtype: str = "bfloat16"
    use_pallas: bool = True
    # src-side gather strategy: "xla" row gather (uniform-random layouts)
    # or "banded" Pallas windowed kernel (after graph/builder.py's
    # cluster_renumber pass narrows per-chunk src id bands — §3b residual)
    src_gather: str = "xla"
    # edge-buffer layout the aggregation ops consume (ISSUE 20,
    # ARCHITECTURE §3v): "coo" is the flat dst-sorted edge list scored
    # as-is; "blocked" additionally ships per-128-dst-row block extents
    # (blocked-CSR row starts computed at window close over the REAL
    # edge prefix) and routes segment reductions through the extent-
    # aware paths — the Pallas kernel skips its on-device binary search
    # and the XLA fallback reduces tile-trimmed instead of rung-padded.
    # Bit-exact vs "coo" by construction (pad edges contribute exactly
    # 0.0); selection is a Python-level branch, so no retraces.
    edge_layout: str = "coo"
    remat: bool = False  # jax.checkpoint each GNN layer (FLOPs for memory)
    # tgn only: pre-size node memory to the largest expected bucket so a
    # growing fleet doesn't pay a serving-time recompile per
    # (bucket, memory-shape) pair
    tgn_max_nodes: int = 4096

    @property
    def edge_feat_dim_in(self) -> int:
        """Edge-feature width as the model layers see it: the raw
        builder features plus the z-scored stat columns when
        ``edge_feat_znorm`` is on (models/common.py znorm_edge_feats)."""
        from alaz_tpu.models.common import EDGE_STAT_COLS

        return self.edge_feature_dim + (EDGE_STAT_COLS if self.edge_feat_znorm else 0)

    @classmethod
    def from_env(cls) -> "ModelConfig":
        return cls(
            model=env_str("MODEL", "graphsage"),
            hidden_dim=env_int("HIDDEN_DIM", 128),
            num_layers=env_int("NUM_LAYERS", 2),
            use_pallas=env_bool("USE_PALLAS", True),
            src_gather=env_str("SRC_GATHER", "xla"),
            edge_layout=env_str("EDGE_LAYOUT", "coo"),
            expert_dispatch=env_str("EXPERT_DISPATCH", "table"),
            edge_feat_znorm=env_bool("EDGE_FEAT_ZNORM", True),
            remat=env_bool("REMAT", False),
            tgn_max_nodes=env_int("TGN_MAX_NODES", 4096),
        )


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh axes for the sharded model (SURVEY §2.3 P1-P7).

    Axis sizes of 1 collapse; the product must divide the device count.
    """

    dp: int = 1  # data parallel: edge-batch shards
    tp: int = 1  # tensor parallel: feature-dim shards
    ep: int = 1  # expert parallel: per-edge-type experts
    sp: int = 1  # sequence/temporal parallel: time-window shards

    @classmethod
    def from_env(cls) -> "MeshConfig":
        return cls(
            dp=env_int("MESH_DP", 1),
            tp=env_int("MESH_TP", 1),
            ep=env_int("MESH_EP", 1),
            sp=env_int("MESH_SP", 1),
        )


def mesh_axis_names() -> tuple:
    """The project mesh vocabulary — MeshConfig's axes, in field order.
    The single source of truth the ALZ024 axis-name rule, the ALZ022
    parity check, and the golden specfiles are verified against
    (tools/alazspec). Lives here (not parallel/sharding.py) so the
    checkers stay importable on jax-less data-plane images."""
    return tuple(f.name for f in dataclasses.fields(MeshConfig))


@dataclass
class RuntimeConfig:
    """Top-level wiring config — the main.go:28-188 analog."""

    queues: QueueConfig = field(default_factory=QueueConfig)
    backend: BackendConfig = field(default_factory=BackendConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    window_s: float = 1.0  # graph snapshot window
    k8s_enabled: bool = True
    # explicit apiserver URL (tests / out-of-cluster); empty = in-cluster
    # serviceaccount discovery (KUBERNETES_SERVICE_HOST + mounted token).
    # Token/CA for the override: the token file is re-read per request
    # (bound tokens rotate on disk)
    k8s_api_server: str = ""
    k8s_token_file: str = ""
    k8s_ca_file: str = ""
    exclude_namespaces: str = ""
    send_alive_tcp_connections: bool = False
    # True only when tracked pids are processes of THIS node (live-agent
    # mode): gates the zombie reaper's <proc_root>/<pid> existence probe
    # and the cold-start backfill — replayed/remote pids must never be
    # probed against this node's procfs
    local_pids: bool = False
    # procfs root for pid liveness probes and cold-start backfill:
    # /host/proc when containerized with the host procfs mounted
    proc_root: str = "/proc"
    # per-window cluster_renumber locality pass (pairs with
    # ModelConfig.src_gather="banded"); incompatible with the temporal
    # model's cross-window node memory — Service refuses the combination
    renumber_nodes: bool = False
    # ingest-idle grace before open windows flush (traffic-lull liveness).
    # Deliberately much larger than a window: a flush during an upstream
    # delivery STALL (agent buffering through a network hiccup) drops the
    # stalled rows as late when they arrive — size this above the longest
    # stall worth riding out, not at the window length.
    idle_flush_grace_s: float = 30.0
    # sharded host ingest (aggregator/sharded.py): >1 partitions L7/TCP
    # traffic by connection key across this many shard workers with a
    # merge thread recombining per-window partials — the serial
    # Aggregator+WindowedGraphStore pair otherwise. Scaling is bounded
    # by cores and the GIL-held fraction of process_l7 (ARCHITECTURE
    # §3f); size to physical cores, not hyperthreads.
    ingest_workers: int = 1
    # sharded-ingest backend (ISSUE 15, ARCHITECTURE §3r): "thread" runs
    # the shard workers as threads over the shared interner (GIL-bound —
    # measured 1.22× at 2 workers); "process" runs them as spawned
    # PROCESSES over shared-memory rings (alaz_tpu/shm) with a
    # per-process interner and id-exchange at merge — the out-of-GIL
    # path. Bit-identical output either way (property-tested); process
    # mode refuses an export tee (worker rows carry local interner ids)
    # and needs a picklable label_fn. "process" also applies at
    # ingest_workers == 1 (ingest leaves the serving process's GIL).
    ingest_backend: str = "thread"
    # L7 engine body backend (ISSUE 16, ARCHITECTURE §3s): "python" runs
    # _process_l7_inner's join/attribution/row-fill as the numpy stage;
    # "native" routes it through alz_process_l7 in libalaz_ingest.so —
    # one C++ pass per batch, GIL held only for the block handoff.
    # Bit-identical output (parity-tested); falls back to "python" with
    # a warning when the .so is unavailable. env-reading DEFAULT (not
    # just from_env) so spawned shard processes and chaos pipelines that
    # build a plain RuntimeConfig() still honor ENGINE_BACKEND=native.
    engine_backend: str = field(
        default_factory=lambda: env_str("ENGINE_BACKEND", "python")
    )
    # edge-buffer layout at window close (ISSUE 20, ARCHITECTURE §3v):
    # "coo" ships the flat dst-sorted list; "blocked" additionally
    # emits per-128-dst-row block extents consumed by the extent-aware
    # aggregation paths. Must match ModelConfig.edge_layout on the
    # scorer side. env-reading DEFAULT (not just from_env) so spawned
    # shard processes and chaos pipelines that build a plain
    # RuntimeConfig() still honor EDGE_LAYOUT=blocked.
    edge_layout: str = field(
        default_factory=lambda: env_str("EDGE_LAYOUT", "coo")
    )
    # shm ring geometry (process backend only; alazspec pins the layout
    # in wire_layouts.json `shm_ring`): bytes per fixed slot and slots
    # per ring. A scattered chunk must fit in ring_slots - 1 slots;
    # per-worker cost is 2 rings × slot_bytes × ring_slots of /dev/shm.
    shm_slot_bytes: int = 65_536
    shm_ring_slots: int = 512
    # multi-tenant serving plane (ISSUE 14, runtime/tenancy.py): >1
    # partitions the HOST plane per tenant — each tenant gets its own
    # interner namespace, drop ledger, source queues, watermarks and
    # windowed pipeline (serial or sharded per ingest_workers), so one
    # tenant's backlog, malformed stream or hot key cannot stall or
    # corrupt another's windows — while all tenants' close waves share
    # ONE scorer: same-bucket windows from different tenants pack into
    # the bucketed staging arenas (score_batch_windows groups), so the
    # device never idles between tenants. Frames carry the tenant id in
    # the header (sources/ingest_server.py); legacy frames are tenant 0.
    # 1 = today's single-tenant wiring, bit-identical (the K=1 parity
    # contract). Bounded by the header byte: ≤ events.schema.MAX_TENANTS.
    tenants: int = 1
    # scatter backpressure bound (aggregator/sharded.py, ISSUE 6): a
    # producer blocks at most this long on a backlogged shard queue
    # before the rows SHED to the drop ledger — a stalled or dead worker
    # costs attributed data, never a wedged submitter. Size above the
    # longest GC-or-merge pause a healthy worker takes, well below any
    # upstream socket timeout.
    shed_block_s: float = 5.0
    # degree-capped reservoir sampling at window close (ISSUE 7,
    # graph/builder.py): bound every dst's aggregated fan-in to this
    # many edges — the hot-key defense (one service with in-degree ~N
    # otherwise turns each window into an N-row batch). 0 = unlimited
    # (bit-identical to the uncapped path). Deterministic per
    # (sample_seed, window, dst-uid, src-uid); cut rows attribute to the
    # ledger's `sampled` cause. Size well above the fleet's honest
    # fan-in (p99.9 of per-service callers), well below the bucket
    # ladder's top rung.
    degree_cap: int = 0
    sample_seed: int = 0
    # deterministic fault injection (alaz_tpu/chaos) — off unless the
    # chaos harness / bench / env flips it
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    # window-lifecycle tracing + flight recorder (ISSUE 9, alaz_tpu/obs)
    # — ON by default; the bench overhead A/B keeps it honest
    trace: TraceConfig = field(default_factory=TraceConfig)
    # scorer backlog micro-batching: when >1 and the model is
    # window-independent (not tgn), up to this many ALREADY-QUEUED
    # same-bucket windows are stacked and scored through one vmapped
    # dispatch — zero added latency when current (only a backlog
    # batches), amortized dispatch overhead when behind
    # (ARCHITECTURE §3e's measured ~190 ms/dispatch through the relay)
    score_batch_windows: int = 1

    @classmethod
    def from_env(cls) -> "RuntimeConfig":
        return cls(
            queues=QueueConfig.from_env(),
            backend=BackendConfig.from_env(),
            model=ModelConfig.from_env(),
            mesh=MeshConfig.from_env(),
            window_s=env_float("WINDOW_S", 1.0),
            k8s_enabled=env_bool("K8S_COLLECTOR_ENABLED", True),
            k8s_api_server=env_str("K8S_API_SERVER", ""),
            k8s_token_file=env_str("K8S_TOKEN_FILE", ""),
            k8s_ca_file=env_str("K8S_CA_FILE", ""),
            exclude_namespaces=env_str("EXCLUDE_NAMESPACES", ""),
            send_alive_tcp_connections=env_bool("SEND_ALIVE_TCP_CONNECTIONS", False),
            local_pids=env_bool("LOCAL_PIDS", False),
            proc_root=env_str("PROC_ROOT", "/proc"),
            renumber_nodes=env_bool("RENUMBER_NODES", False),
            idle_flush_grace_s=env_float("IDLE_FLUSH_GRACE_S", 30.0),
            ingest_workers=env_int("INGEST_WORKERS", 1),
            ingest_backend=env_str("INGEST_BACKEND", "thread"),
            engine_backend=env_str("ENGINE_BACKEND", "python"),
            edge_layout=env_str("EDGE_LAYOUT", "coo"),
            shm_slot_bytes=env_int("SHM_SLOT_BYTES", 65_536),
            shm_ring_slots=env_int("SHM_RING_SLOTS", 512),
            tenants=env_int("TENANTS", 1),
            shed_block_s=env_float("SHED_BLOCK_S", 5.0),
            degree_cap=env_int("DEGREE_CAP", 0),
            sample_seed=env_int("SAMPLE_SEED", 0),
            chaos=ChaosConfig.from_env(),
            trace=TraceConfig.from_env(),
            score_batch_windows=env_int("SCORE_BATCH_WINDOWS", 1),
        )
