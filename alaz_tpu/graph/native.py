"""ctypes binding for the C++ ingest core (native/ingest.cc).

``NativeIngest`` is the high-throughput path of the windowed graph builder:
REQUEST_DTYPE rows are converted (vectorized) into the 32-byte wire record,
pushed into the native ring, and closed windows come back as aggregated
COO columns from which GraphBatches are assembled with the same feature
schema as the pure-numpy ``GraphBuilder``. Build the library with
``make -C alaz_tpu/native``; ``available()`` gates callers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from pathlib import Path
from typing import Optional

import numpy as np

from alaz_tpu.graph.builder import EDGE_FEATURE_DIM, NODE_FEATURE_DIM
from alaz_tpu.graph.snapshot import GraphBatch

_LIB_DIR = Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _LIB_DIR / "libalaz_ingest.so"

# mirrors struct AlzRecord (ingest.cc); flags: bit0 tls, bit1 failed
NATIVE_RECORD_DTYPE = np.dtype(
    {
        "names": [
            "start_time_ms", "latency_ns", "from_uid", "to_uid",
            "status", "from_type", "to_type", "protocol", "flags",
        ],
        "formats": [
            np.int64, np.uint64, np.int32, np.int32,
            np.uint32, np.uint8, np.uint8, np.uint8, np.uint8,
        ],
        "offsets": [0, 8, 16, 20, 24, 28, 29, 30, 31],
        "itemsize": 32,
    }
)

_lib: Optional[ctypes.CDLL] = None

# ---------------------------------------------------------------------------
# Declarative export table — the single source both `_register` (ctypes
# restype/argtypes) and tools/alazspec (`export_signatures`, pinned in the
# golden wire table) read, so the binding and the spec can never drift
# apart. Type vocabulary: ptr (void*), pptr (void**), i32/u32/i64/u64,
# f32, cstr (const char*), void (no return).
# ---------------------------------------------------------------------------

NATIVE_EXPORTS: dict = {
    "alz_create": ("ptr", ("i64", "u32", "u32", "u32")),
    "alz_destroy": ("void", ("ptr",)),
    "alz_push": ("u32", ("ptr", "ptr", "u32")),
    "alz_drain": ("i64", ("ptr",)),
    "alz_dropped": ("u64", ("ptr",)),
    "alz_ring_dropped": ("u64", ("ptr",)),
    "alz_late_dropped": ("u64", ("ptr",)),
    "alz_acc_dropped": ("u64", ("ptr",)),
    "alz_current_window": ("i64", ("ptr",)),
    "alz_node_count": ("u32", ("ptr",)),
    "alz_close_window": ("i32", ("ptr", "u32") + ("ptr",) * 10),
    "alz_export_nodes": ("u32", ("ptr", "u32", "ptr", "ptr")),
    "alz_current_edge_count": ("i64", ("ptr",)),
    "alz_close_window_feats": (
        "i32",
        ("ptr", "u32", "u32", "ptr", "f32", "u32", "u64") + ("ptr",) * 7,
    ),
    "alz_process_l7": (
        "i64",
        ("ptr", "i64", "u64",  # events, n, now_ns
         "ptr", "ptr", "ptr", "i64",  # sl_pid, sl_fd, sl_off, n_lines
         "ptr", "ptr", "ptr", "ptr", "ptr", "ptr",  # ts/open/saddr/sport/daddr/dport
         "ptr",  # sl_touched (out)
         "ptr", "ptr", "i64",  # pod ips/uids/n
         "ptr", "ptr", "i64",  # svc ips/uids/n
         "ptr", "ptr", "ptr", "ptr"),  # out rows, kept_idx, unmatched_idx, counts
    ),
    "alz_group_edges": (
        "i64",
        ("ptr", "u64", "pptr", "u32", "pptr", "u32", "u64", "ptr", "ptr",
         "ptr", "pptr", "pptr"),
    ),
    "alz_sample_degree_cap": (
        "i64",
        ("ptr", "ptr", "i64", "u32", "ptr", "u64"),
    ),
    "alz_edge_feat_dim": ("u32", ()),
    "alz_node_feat_dim": ("u32", ()),
    "alz_abi_record_layout": ("cstr", ()),
    "alz_abi_l7_event_layout": ("cstr", ()),
    "alz_abi_request_layout": ("cstr", ()),
    "alz_source_hash": ("cstr", ()),
}

# Drop/retry cause order of alz_process_l7's `counts` output vector —
# counts[0] is requeue-or-no_socket (unmatched join), counts[1] is the
# not_pod attribution drop. Pinned in the alazspec l7_engine wire table;
# the aggregator maps them onto DropLedger "filtered" reasons, so a
# reorder here without a spec regen fails tier-1.
L7_ENGINE_DROP_CAUSES = ("no_socket", "not_pod")

# The per-column meaning of alz_close_window's 10 output pointers and
# alz_export_nodes' 2 — every aggregate column after window_start_ms must
# be an EdgeSlot (resp. NodeSlot) field, which tools/alazspec cross-checks
# against the parsed C structs so a renamed/dropped accumulator field
# fails tier-1 instead of silently exporting garbage.
CLOSE_WINDOW_COLUMNS = (
    "window_start_ms", "src_slot", "dst_slot", "protocol", "count",
    "lat_sum", "lat_max", "err5", "err4", "tls_cnt",
)
EXPORT_NODES_COLUMNS = ("uid", "type")

_CTYPE_OF = {
    "ptr": ctypes.c_void_p,
    "pptr": ctypes.POINTER(ctypes.c_void_p),
    "i32": ctypes.c_int32,
    "u32": ctypes.c_uint32,
    "i64": ctypes.c_int64,
    "u64": ctypes.c_uint64,
    "f32": ctypes.c_float,
    "cstr": ctypes.c_char_p,
    "void": None,
}


def export_signatures() -> dict:
    """{export name: "ret(arg, ...)"} — the binding-side half of the
    native-export contract tools/alazspec pins in the golden wire table."""
    return {
        name: f"{ret}({', '.join(args)})"
        for name, (ret, args) in NATIVE_EXPORTS.items()
    }


def record_layout_string() -> str:
    """NATIVE_RECORD_DTYPE rendered in the shared layout-string format
    (events/schema.py dtype_layout) — the Python half of the AlzRecord
    ABI contract the loaded .so must byte-match."""
    from alaz_tpu.events.schema import dtype_layout

    return dtype_layout(NATIVE_RECORD_DTYPE, "AlzRecord")


def l7_event_layout_string() -> str:
    """L7_EVENT_DTYPE's layout string — the input half of the
    alz_process_l7 wire contract (AlzL7Event mirror in ingest.cc)."""
    from alaz_tpu.events.schema import L7_EVENT_DTYPE, dtype_layout

    return dtype_layout(L7_EVENT_DTYPE, "AlzL7Event")


def request_layout_string() -> str:
    """REQUEST_DTYPE's layout string — the output half of the
    alz_process_l7 wire contract (AlzRequest mirror in ingest.cc)."""
    from alaz_tpu.datastore.dto import REQUEST_DTYPE
    from alaz_tpu.events.schema import dtype_layout

    return dtype_layout(REQUEST_DTYPE, "AlzRequest")


def loaded_source_hash() -> Optional[str]:
    """``alz_source_hash()`` of the loaded .so ("unstamped" for
    out-of-band builds), or None when the library is unavailable — the
    staleness-guard input for tools/alazspec."""
    lib = _load()
    if lib is None:
        return None
    return lib.alz_source_hash().decode()


def build(force: bool = False) -> bool:
    """Compile the shared library if needed; True on success. Always runs
    make (a no-op when up to date) so an edited ingest.cc is never shadowed
    by a stale .so."""
    try:
        cmd = ["make", "-C", str(_LIB_DIR)]
        if force:
            cmd.append("-B")
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _LIB_PATH.exists()
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError):
        return _LIB_PATH.exists()


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    # ALZ_NATIVE_LIB points the whole binding at an alternate build of the
    # same exports — the seam the alaznat fuzz harness (tools/alaznat) uses
    # to run the ASan/UBSan shared objects through the exact ctypes paths
    # production takes (the sanitizer runtime arrives via LD_PRELOAD in
    # that subprocess). The alternate build passes the same _register
    # layout checks as the default; no other behavior changes.
    alt = os.environ.get("ALZ_NATIVE_LIB")
    if not alt and not build():
        return None
    try:
        lib = ctypes.CDLL(alt if alt else str(_LIB_PATH))
        _register(lib)
    except (OSError, AttributeError):
        # unloadable or stale .so missing newer symbols (e.g. prebuilt lib
        # + no toolchain): fall back to the numpy store gracefully
        return None
    _lib = lib
    return lib


def _register(lib: ctypes.CDLL) -> None:
    # every export's restype/argtypes come from the declarative table —
    # the same table alazspec pins in the golden wire table, so a binding
    # edit without a `make specs` fails tier-1
    for name, (ret, args) in NATIVE_EXPORTS.items():
        fn = getattr(lib, name)  # AttributeError on a stale .so → fallback
        fn.restype = _CTYPE_OF[ret]
        fn.argtypes = [_CTYPE_OF[a] for a in args]
    # feature-layout contract: the C++ pass writes ef/nf rows with these
    # strides — a drifted constant would silently misalign every feature.
    # RuntimeError on purpose: _load's except clause swallows
    # OSError/AttributeError (stale-.so fallback), but THIS condition must
    # surface loudly, not degrade to the numpy path without a signal.
    if (lib.alz_edge_feat_dim(), lib.alz_node_feat_dim()) != (
        EDGE_FEATURE_DIM, NODE_FEATURE_DIM,
    ):
        raise RuntimeError(
            "libalaz_ingest.so feature dims drifted from graph/builder.py; "
            "rebuild with make -C alaz_tpu/native -B"
        )
    # record-layout contract: the binary's own offsetof/sizeof table must
    # byte-match NATIVE_RECORD_DTYPE — same loud-failure rationale. The
    # source↔binary↔dtype triangle is closed by tools/alazspec (ALZ020).
    compiled = lib.alz_abi_record_layout().decode()
    if compiled != record_layout_string():
        raise RuntimeError(
            "libalaz_ingest.so AlzRecord layout drifted from "
            f"NATIVE_RECORD_DTYPE:\n  .so:   {compiled}\n"
            f"  dtype: {record_layout_string()}\n"
            "rebuild with make -C alaz_tpu/native -B"
        )
    # L7 engine wire mirrors (ISSUE 16): alz_process_l7 reads L7_EVENT_DTYPE
    # bytes and writes REQUEST_DTYPE bytes directly — same loud-failure
    # rationale as AlzRecord, for both directions of the handoff.
    for fn_name, want in (
        ("alz_abi_l7_event_layout", l7_event_layout_string()),
        ("alz_abi_request_layout", request_layout_string()),
    ):
        compiled = getattr(lib, fn_name)().decode()
        if compiled != want:
            raise RuntimeError(
                f"libalaz_ingest.so {fn_name} drifted from the pinned "
                f"dtype:\n  .so:   {compiled}\n  dtype: {want}\n"
                "rebuild with make -C alaz_tpu/native -B"
            )


def available() -> bool:
    return _load() is not None


def _ptr_array(arrays) -> "ctypes.Array":
    """numpy float64 arrays → C `double*[]` (void** at the ctypes level)."""
    return (ctypes.c_void_p * max(len(arrays), 1))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays] or [None]
    )


def group_edges(keys, sum_cols, max_cols):
    """Grouped reduction through the C++ core (``alz_group_edges``):
    group rows by int64 key, per-group count + SUMs over ``sum_cols`` +
    MAXes over ``max_cols``. Returns ``(uniq_keys, count, rep, sums,
    maxes)`` in ascending key order, or None when the library is
    unavailable (callers fall back to the numpy argsort+reduceat path —
    graph/builder.group_reduce). Stateless and thread-safe: the sharded
    ingest workers call it concurrently."""
    lib = _load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    n = keys.shape[0]
    sc = [np.ascontiguousarray(c, dtype=np.float64) for c in sum_cols]
    mc = [np.ascontiguousarray(c, dtype=np.float64) for c in max_cols]
    out_keys = np.empty(n, dtype=np.int64)
    out_count = np.empty(n, dtype=np.float64)
    out_rep = np.empty(n, dtype=np.int64)
    out_sums = [np.empty(n, dtype=np.float64) for _ in sc]
    out_maxes = [np.empty(n, dtype=np.float64) for _ in mc]
    ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    pptr = lambda arrs: ctypes.cast(_ptr_array(arrs), ctypes.POINTER(ctypes.c_void_p))  # noqa: E731
    e = int(
        lib.alz_group_edges(
            ptr(keys), n, pptr(sc), len(sc), pptr(mc), len(mc), n,
            ptr(out_keys), ptr(out_count), ptr(out_rep),
            pptr(out_sums), pptr(out_maxes),
        )
    )
    if e < 0:  # can't happen with out_cap == n; belt and braces
        return None
    return (
        out_keys[:e], out_count[:e], out_rep[:e],
        [s[:e] for s in out_sums], [m[:e] for m in out_maxes],
    )


def sample_degree_cap(dst, prio, cap: int):
    """Degree-capped bottom-k selection through the C++ core
    (``alz_sample_degree_cap``): over DST-SORTED aggregated edges, keep
    at most ``cap`` edges per dst — the ones with the smallest 64-bit
    priorities (ties by ascending row index, matching the numpy
    fallback's stable lexsort bit for bit). Returns kept indices in
    ascending order, or None when the library is unavailable (callers
    fall back to graph/builder.py's numpy path). Stateless and
    thread-safe like ``alz_group_edges``."""
    lib = _load()
    if lib is None:
        return None
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    prio = np.ascontiguousarray(prio, dtype=np.uint64)
    n = int(dst.shape[0])
    out = np.empty(n, dtype=np.int64)
    ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    k = int(
        lib.alz_sample_degree_cap(
            ptr(dst), ptr(prio), n, int(cap), ptr(out), n
        )
    )
    if k < 0:  # cap==0 or short buffer: both are caller bugs — fall back
        return None
    return out[:k]


_INT64_MIN = -(2**63)


class NativeWindowedStore:
    """DataStore adapter over NativeIngest — drop-in for
    WindowedGraphStore when the C++ core is available: persist_requests
    pushes into the ring and polls closed windows to ``on_batch``."""

    def __init__(self, window_s: float = 1.0, on_batch=None, **kwargs):
        self.ingest = NativeIngest(window_s=window_s, **kwargs)
        self.on_batch = on_batch
        self.batches: list[GraphBatch] = []
        self.request_count = 0
        self.last_persist_monotonic: float | None = None
        # the C++ side is single-consumer (alz_drain/alz_close_window share
        # ring tail + export buffers); serialize like WindowedGraphStore does
        self._lock = threading.Lock()

    @property
    def late_dropped(self) -> int:
        return self.ingest.late_dropped

    @property
    def ring_dropped(self) -> int:
        return self.ingest.ring_dropped

    @property
    def acc_dropped(self) -> int:
        return self.ingest.acc_dropped

    @property
    def sampled_edges(self) -> int:
        return self.ingest.sampled_edges

    @property
    def sampled_rows(self) -> int:
        return self.ingest.sampled_rows

    def persist_requests(self, batch: np.ndarray) -> None:
        with self._lock:
            self.last_persist_monotonic = time.monotonic()
            self.request_count += batch.shape[0]
            self.ingest.push(batch)
            while True:
                out = self.ingest.poll()
                if out is None:
                    break
                self._emit(out)

    def push_records(self, rows: np.ndarray) -> int:
        """Pre-packed NATIVE_RECORD_DTYPE rows (the socket fast path:
        agents ship AlzRecord wire bytes, no REQUEST_DTYPE conversion).
        Returns accepted count; closed windows emit as usual."""
        with self._lock:
            self.last_persist_monotonic = time.monotonic()
            self.request_count += rows.shape[0]
            accepted = self.ingest.push_records(rows)
            while True:
                out = self.ingest.poll()
                if out is None:
                    break
                self._emit(out)
            return accepted

    def persist_kafka_events(self, batch: np.ndarray) -> None:
        pass

    def persist_alive_connections(self, batch: np.ndarray) -> None:
        pass

    def persist_resource(self, rtype, event, obj) -> None:
        pass

    def flush(self) -> None:
        with self._lock:
            for out in self.ingest.flush():
                self._emit(out)

    def _emit(self, batch: GraphBatch) -> None:
        if self.on_batch is not None:
            self.on_batch(batch)
        else:
            self.batches.append(batch)

    def close(self) -> None:
        with self._lock:
            self.ingest.close()


class NativeIngest:
    """Windowed edge aggregation backed by the C++ core.

    Usage: ``push(request_rows)`` (drop-not-block), then ``poll()`` which
    returns a GraphBatch whenever a window closed.
    """

    def __init__(
        self,
        window_s: float = 1.0,
        ring_capacity: int = 1 << 18,
        max_edges: int = 1 << 20,
        max_nodes: int = 1 << 20,
        renumber: bool = False,
        degree_cap: int = 0,
        sample_seed: int = 0,
        ledger=None,
        edge_layout: Optional[str] = None,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("libalaz_ingest.so unavailable; run make -C alaz_tpu/native")
        self._lib = lib
        self.window_ms = int(window_s * 1000)
        self.window_s = window_s
        self.max_edges = max_edges
        self.max_nodes = max_nodes
        # the locality pass runs host-side on the exported arrays — the
        # C++ core's internal slot assignment is untouched
        self.renumber = renumber
        # per-dst fan-in cap folded into the close pass (ISSUE 16): the
        # C++ side draws the SAME sample_priorities(seed, window, uids,
        # proto) bottom-k as graph/builder.py degree_cap_select, so the
        # native close and the numpy builder select identical survivors
        self.degree_cap = int(degree_cap)
        self.sample_seed = int(sample_seed)
        self.ledger = ledger
        # blocked-extent REFUSAL surface (ISSUE 20, pinned in
        # resources/specs/wire_layouts.json `edge_blocks`): the C export
        # does NOT ship block extents — alz_close_window_feats' signature
        # is frozen (ALZ030 offsets golden) and the extents are a pure
        # function of the dst-sorted columns it already emits, so the
        # python side derives them instead: one np.searchsorted over the
        # int32 dst prefix (~µs/window, next to the close pass's ms).
        # Growing the C ABI for a value the host recomputes for free
        # would buy nothing and cost an offsets/parity churn.
        from alaz_tpu.config import env_str

        self.edge_layout = (
            edge_layout if edge_layout is not None
            else env_str("EDGE_LAYOUT", "coo")
        )
        self.sampled_edges = 0
        self.sampled_rows = 0
        self._h = ctypes.c_void_p(
            lib.alz_create(self.window_ms, ring_capacity, max_edges, max_nodes)
        )

    def close(self) -> None:
        if self._h:
            self._lib.alz_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    @property
    def dropped(self) -> int:
        if not self._h:
            return 0  # closed: metrics gauges may still poll
        return int(self._lib.alz_dropped(self._h))

    @property
    def ring_dropped(self) -> int:
        """Backpressure drops (ring full), separate from lateness drops."""
        if not self._h:
            return 0
        return int(self._lib.alz_ring_dropped(self._h))

    @property
    def late_dropped(self) -> int:
        """Rows dropped because their window was already emitted."""
        if not self._h:
            return 0
        return int(self._lib.alz_late_dropped(self._h))

    @property
    def acc_dropped(self) -> int:
        """Rows dropped on node/edge table capacity."""
        if not self._h:
            return 0
        return int(self._lib.alz_acc_dropped(self._h))

    @staticmethod
    def to_records(rows: np.ndarray) -> np.ndarray:
        """REQUEST_DTYPE rows → packed native records (vectorized)."""
        out = np.zeros(rows.shape[0], dtype=NATIVE_RECORD_DTYPE)
        out["start_time_ms"] = rows["start_time_ms"]
        out["latency_ns"] = rows["latency_ns"]
        out["from_uid"] = rows["from_uid"]
        out["to_uid"] = rows["to_uid"]
        out["status"] = rows["status_code"]
        out["from_type"] = rows["from_type"]
        out["to_type"] = rows["to_type"]
        out["protocol"] = rows["protocol"]
        out["flags"] = rows["tls"].astype(np.uint8) | (
            (~rows["completed"]).astype(np.uint8) << 1
        )
        return out

    def push(self, rows: np.ndarray) -> int:
        """Push REQUEST_DTYPE rows; returns accepted count."""
        if not self._h:
            return 0
        recs = self.to_records(np.ascontiguousarray(rows))
        return self.push_records(recs)

    def push_records(self, recs: np.ndarray) -> int:
        """Push already-packed NATIVE_RECORD_DTYPE rows."""
        if not self._h:
            return 0
        recs = np.ascontiguousarray(recs)
        return int(
            self._lib.alz_push(
                self._h, recs.ctypes.data_as(ctypes.c_void_p), recs.shape[0]
            )
        )

    def poll(self) -> Optional[GraphBatch]:
        """Drain the ring; if a window closed, build and return its batch."""
        if not self._h:
            return None
        ready = int(self._lib.alz_drain(self._h))
        if ready == _INT64_MIN:
            return None
        return self._close_current()

    def flush(self) -> list[GraphBatch]:
        """Drain everything and close every open window, oldest first."""
        out: list[GraphBatch] = []
        if not self._h:
            return out
        while True:
            ready = int(self._lib.alz_drain(self._h))
            if ready == _INT64_MIN:
                break
            out.append(self._close_current())
        while int(self._lib.alz_current_window(self._h)) != _INT64_MIN:
            out.append(self._close_current())
        return out

    def _close_current(self) -> GraphBatch:
        """Close the oldest window via the C++ feature-assembly pass.

        The core emits dst-sorted COO columns plus both feature matrices
        straight into the padded numpy buffers the GraphBatch keeps, so
        the former numpy stage (argsort + 8 bincounts + log1p features +
        pad copies — ~120 ms per 256k-edge window) collapses to buffer
        allocation and pad fills."""
        from alaz_tpu.graph.snapshot import pad_to_bucket

        e = int(self._lib.alz_current_edge_count(self._h))
        if e < 0:
            raise RuntimeError("alz_close_window called with no open window")
        n_nodes = int(self._lib.alz_node_count(self._h))
        e_pad = pad_to_bucket(e)
        n_pad = pad_to_bucket(n_nodes)

        es = np.zeros(e_pad, np.int32)
        ed = np.zeros(e_pad, np.int32)
        et = np.zeros(e_pad, np.int32)
        cnt = np.zeros(e_pad, np.uint64)
        ef = np.zeros((e_pad, EDGE_FEATURE_DIM), np.float32)
        nf = np.zeros((n_pad, NODE_FEATURE_DIM), np.float32)
        ws = ctypes.c_int64(0)
        sampled = np.zeros(2, np.int64)  # [cut_edges, cut_rows]
        ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
        n = int(
            self._lib.alz_close_window_feats(
                self._h, e_pad, n_pad, ctypes.byref(ws),
                ctypes.c_float(self.window_s),
                self.degree_cap, self.sample_seed,
                ptr(es), ptr(ed), ptr(et), ptr(cnt), ptr(ef), ptr(nf),
                ptr(sampled),
            )
        )
        if n == -2:
            raise RuntimeError("alz_close_window called with no open window")
        if n == -3:
            raise RuntimeError("native node buffer too small; raise max_nodes")
        if n < 0:
            raise RuntimeError("native edge buffer overflow; raise max_edges")
        if sampled[0]:
            self.sampled_edges += int(sampled[0])
            self.sampled_rows += int(sampled[1])
            if self.ledger is not None:
                self.ledger.add("sampled", int(sampled[1]), reason="degree_cap")

        uids = np.zeros(n_pad, np.int32)
        types = np.zeros(n_pad, np.uint8)
        self._lib.alz_export_nodes(self._h, n_pad, ptr(uids), ptr(types))
        node_type = types.astype(np.int32)
        window_start_ms = int(ws.value)

        if self.renumber and n > 0:
            # the locality pass permutes node ids, which invalidates the
            # core's dst-sort — rebuild (re-sort) through GraphBatch.build
            from alaz_tpu.graph.builder import apply_renumber, cluster_renumber

            perm = cluster_renumber(
                es[:n], ed[:n], n_nodes, edge_weight=cnt[:n].astype(np.float64)
            )
            src, dst, rnf, rnt, ruids = apply_renumber(
                perm, es[:n], ed[:n], nf[:n_nodes], node_type[:n_nodes],
                uids[:n_nodes],
            )
            return self._finish(GraphBatch.build(
                node_feats=rnf,
                node_type=rnt,
                edge_src=src,
                edge_dst=dst,
                edge_type=et[:n],
                edge_feats=ef[:n],
                node_uids=ruids,
                window_start_ms=window_start_ms,
                window_end_ms=window_start_ms + self.window_ms,
            ))

        return self._finish(GraphBatch.from_presorted(
            nf, node_type, es, ed, et, ef, n_nodes, n,
            node_uids=uids,
            window_start_ms=window_start_ms,
            window_end_ms=window_start_ms + self.window_ms,
        ))

    def _finish(self, batch: GraphBatch) -> GraphBatch:
        """Post-close layout step shared by both close paths: under the
        blocked layout, derive the extents python-side at close time
        (the refusal surface documented in __init__ — the C core emits
        dst-sorted columns, which is all the searchsorted needs) so
        downstream staging/telemetry see the same eager window invariant
        the numpy builder ships."""
        if self.edge_layout == "blocked":
            batch.block_starts()
        return batch
