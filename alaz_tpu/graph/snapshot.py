"""GraphBatch: the static-shape unit of device work.

XLA compiles one program per distinct shape, so dynamic service graphs are
padded to **bucketed** sizes (next power-of-two-ish), the padding masked
out. This is SURVEY §7's hard part (a): the padding/bucketing policy is the
perf lever — buckets too fine cause recompiles, too coarse waste FLOPs.

All arrays are plain numpy here; ``to_device`` views are whatever jnp makes
of them. Fields:

- ``node_feats``  [N_pad, F]   float32 (cast to bf16 inside the model)
- ``node_type``   [N_pad]      int32 (EP_* codes)
- ``node_mask``   [N_pad]      bool
- ``edge_src/dst``[E_pad]      int32 (indices into the node axis)
- ``edge_type``   [E_pad]      int32 (L7Protocol codes — GAT edge-type
                                embeddings, BASELINE.json config 3)
- ``edge_feats``  [E_pad, Fe]  float32
- ``edge_mask``   [E_pad]      bool
- ``edge_label``  [E_pad]      float32 (fault labels when known; else 0)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

_BUCKET_STEPS = (128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 65536, 131072, 262144, 524288, 1048576)

# dst-block geometry of the blocked edge layout (ISSUE 20, ARCHITECTURE
# §3v): one block per 128 node rows — the MXU/VPU lane width and the
# Pallas scatter kernel's one-hot row chunk (ops/pallas_segment.py).
# Every bucket rung is a multiple of this, so extents always tile.
EDGE_BLOCK_ROWS = 128


def edge_block_starts_from(
    edge_dst: np.ndarray, n_edges: int, n_pad: int
) -> np.ndarray:
    """Blocked-CSR row starts over the REAL edge prefix: entry ``b`` is
    the first edge whose dst lands at or past node row 128·b, so dst
    block ``b`` owns edges ``[starts[b], starts[b+1])`` and
    ``starts[-1] == n_edges`` is the live-edge frontier the blocked
    aggregation paths trim to. THE one extent definition — the builder
    close path, the native close path and the per-batch lazy field all
    route through it, so the `edge_blocks` wire contract
    (resources/specs/wire_layouts.json) cannot drift per producer.
    Precondition: ``edge_dst[:n_edges]`` dst-sorted (the GraphBatch
    layout invariant). The pad tail (dst pinned to n_pad-1 past
    n_edges) is excluded by the prefix slice, so pad edges are invisible
    to the extents and contribute exactly 0.0 under masking — blocked
    and COO reductions are bit-exact, not merely close."""
    bounds = np.arange(0, n_pad + 1, EDGE_BLOCK_ROWS, dtype=np.int64)
    return np.searchsorted(edge_dst[:n_edges], bounds, side="left").astype(
        np.int32
    )


def blocked_edge_slots_from(block_starts: np.ndarray) -> int:
    """Edge-tile slots the blocked aggregation paths actually touch:
    each NONEMPTY dst block costs its extent rounded out to whole
    128-edge tiles (a tile straddled by two blocks is charged to both —
    the ELL cost model); empty blocks cost nothing. The numerator of
    ``block_fill_pct`` (obs/device.py) beside ``pad_waste_pct``'s
    bucket-rung denominator."""
    bs = block_starts.astype(np.int64)
    lo, hi = bs[:-1], bs[1:]
    tiles = np.where(
        hi > lo,
        -(-hi // EDGE_BLOCK_ROWS) - lo // EDGE_BLOCK_ROWS,
        0,
    )
    return int(tiles.sum()) * EDGE_BLOCK_ROWS


def pad_to_bucket(n: int, minimum: int = 128) -> int:
    """Next bucket ≥ n: powers of two with 1.5× midpoints (from 256 up, so
    every bucket stays a multiple of 128 — the Pallas tile requirement),
    capping padding waste at ~25% while keeping the shape count small."""
    n = max(n, minimum)
    for b in _BUCKET_STEPS:
        if n <= b:
            return b
        mid = b + b // 2
        if b >= 256 and n <= mid:
            return mid
    return int(2 ** np.ceil(np.log2(n)))


@dataclass
class GraphBatch:
    node_feats: np.ndarray  # [N_pad, F] f32
    node_type: np.ndarray  # [N_pad] i32
    node_mask: np.ndarray  # [N_pad] bool
    edge_src: np.ndarray  # [E_pad] i32
    edge_dst: np.ndarray  # [E_pad] i32
    edge_type: np.ndarray  # [E_pad] i32
    edge_feats: np.ndarray  # [E_pad, Fe] f32
    edge_mask: np.ndarray  # [E_pad] bool
    edge_label: np.ndarray  # [E_pad] f32
    n_nodes: int
    n_edges: int
    window_start_ms: int = 0
    window_end_ms: int = 0
    # node slot -> interned uid (host-side bookkeeping, not shipped to device)
    node_uids: Optional[np.ndarray] = field(default=None, repr=False)
    # [N_pad] f32 masked in-degree — a WINDOW INVARIANT, so it is
    # computed once on the host (one bincount) instead of per dispatch
    # on the device: the in-graph segment_sum XLA lowers it to on TPU
    # costs a [E]-pair sort + reduce (~10 ms at the 1M-edge bucket,
    # r03 trace — hoisted out of the bench loop by LICM but paid by
    # EVERY serve-side window). Lazily filled by device_arrays.
    node_deg: Optional[np.ndarray] = field(default=None, repr=False)
    # [N_pad//128 + 1] i32 blocked-CSR row starts over the real edge
    # prefix (ISSUE 20) — a WINDOW INVARIANT like node_deg, computed
    # once on the host (one searchsorted over the dst-sorted prefix)
    # and shipped only when the blocked layout is selected. Lazily
    # filled by block_starts(); the builder/native close paths fill it
    # eagerly under EDGE_LAYOUT=blocked so close-time accounting sees it.
    edge_block_starts: Optional[np.ndarray] = field(default=None, repr=False)

    @property
    def n_pad(self) -> int:
        return self.node_feats.shape[0]

    @property
    def e_pad(self) -> int:
        return self.edge_src.shape[0]

    # -- bucket capacity surface (ISSUE 11) ----------------------------------
    # the device plane's accounting vocabulary: one bucket label per
    # compiled program shape, pad tail = the FLOPs the padding policy is
    # spending to avoid a recompile

    @property
    def bucket_key(self) -> str:
        """The (node, edge) capacity label this batch scores under —
        exactly the pair keying the jit cache."""
        return f"n{self.n_pad}xe{self.e_pad}"

    @property
    def pad_edge_slots(self) -> int:
        """Edge slots in the bucket that carry padding, not data."""
        return self.e_pad - self.n_edges

    @property
    def edge_occupancy(self) -> float:
        """Real-edge fraction of the edge bucket (0..1)."""
        return self.n_edges / self.e_pad if self.e_pad else 0.0

    def aggregated_rows(self) -> int:
        """Exact request-row count this window aggregated: edge feature
        0 is log1p(request count), so the inverse recovers the integer
        total. THE row measure of every conservation equation (chaos
        gates, per-tenant isolation gates, window-shed attribution) —
        one definition, so the books can never disagree about what a
        window weighed."""
        return int(
            np.rint(np.expm1(self.edge_feats[: self.n_edges, 0])).sum()
        )

    def block_starts(self) -> np.ndarray:
        """The blocked layout's per-128-dst-row extents (lazy window
        invariant, see ``edge_block_starts_from``)."""
        if self.edge_block_starts is None:
            self.edge_block_starts = edge_block_starts_from(
                self.edge_dst, self.n_edges, self.n_pad
            )
        return self.edge_block_starts

    @property
    def blocked_edge_slots(self) -> int:
        """Edge-tile slots the blocked paths touch for this window."""
        return blocked_edge_slots_from(self.block_starts())

    def device_arrays(self, edge_layout: str = "coo") -> dict:
        """The pytree the jit'd model consumes (static shapes only).
        ``edge_layout="blocked"`` adds the ``edge_block_starts`` extents
        — a DIFFERENT pytree structure, so the two layouts compile (and
        cache) as separate programs; per layout the structure is fixed,
        so selection costs zero retraces (alazjit-pinned)."""
        if self.node_deg is None:
            # pad edges sit masked on the last node slot and are excluded
            # by the [:n_edges] slice, so this equals the in-model
            # masked_degree exactly (models/common.py)
            self.node_deg = np.bincount(
                self.edge_dst[: self.n_edges], minlength=self.n_pad
            ).astype(np.float32)
        out = {
            "node_feats": self.node_feats,
            "node_type": self.node_type,
            "node_mask": self.node_mask,
            "node_deg": self.node_deg,
            "edge_src": self.edge_src,
            "edge_dst": self.edge_dst,
            "edge_type": self.edge_type,
            "edge_feats": self.edge_feats,
            "edge_mask": self.edge_mask,
        }
        if edge_layout == "blocked":
            out["edge_block_starts"] = self.block_starts()
        return out

    @staticmethod
    def from_presorted(
        node_feats: np.ndarray,
        node_type: np.ndarray,
        edge_src: np.ndarray,
        edge_dst: np.ndarray,
        edge_type: np.ndarray,
        edge_feats: np.ndarray,
        n_nodes: int,
        n_edges: int,
        node_uids: Optional[np.ndarray] = None,
        window_start_ms: int = 0,
        window_end_ms: int = 0,
    ) -> "GraphBatch":
        """Wrap ALREADY dst-sorted, already PADDED arrays (the C++ core's
        export path) into a GraphBatch. Owns the pad-slot policy so it
        cannot diverge from ``build``: pad dsts land on the masked last
        node slot (segment ops dump there instead of polluting node 0),
        pad srcs repeat the last real src (a far-away pad id would blow
        the straddling chunk's [min,max] band and cliff the banded
        gather — ops/pallas_segment.py gather_rows_banded).

        OWNERSHIP TRANSFER: the input arrays become the batch's arrays —
        no copies — and the edge_src/edge_dst pad tails are rewritten in
        place. Callers must hand over freshly allocated, writable
        buffers and not reuse them afterwards (both internal callers
        allocate per window)."""
        e_pad = edge_src.shape[0]
        n_pad = node_feats.shape[0]
        edge_src[n_edges:] = edge_src[n_edges - 1] if n_edges > 0 else 0
        edge_dst[n_edges:] = n_pad - 1
        edge_mask = np.zeros(e_pad, dtype=bool)
        edge_mask[:n_edges] = True
        node_mask = np.zeros(n_pad, dtype=bool)
        node_mask[:n_nodes] = True
        return GraphBatch(
            node_feats=node_feats,
            node_type=node_type,
            node_mask=node_mask,
            edge_src=edge_src,
            edge_dst=edge_dst,
            edge_type=edge_type,
            edge_feats=edge_feats,
            edge_mask=edge_mask,
            edge_label=np.zeros(e_pad, dtype=np.float32),
            n_nodes=n_nodes,
            n_edges=n_edges,
            window_start_ms=window_start_ms,
            window_end_ms=window_end_ms,
            node_uids=node_uids,
        )

    @staticmethod
    def build(
        node_feats: np.ndarray,
        node_type: np.ndarray,
        edge_src: np.ndarray,
        edge_dst: np.ndarray,
        edge_type: np.ndarray,
        edge_feats: np.ndarray,
        edge_label: Optional[np.ndarray] = None,
        node_uids: Optional[np.ndarray] = None,
        window_start_ms: int = 0,
        window_end_ms: int = 0,
        sort_by_dst: bool = True,
    ) -> "GraphBatch":
        """Pad/bucket raw COO arrays into a GraphBatch. Edges are sorted by
        destination so segment reductions see contiguous runs (the layout
        the Pallas kernel requires)."""
        n = int(node_feats.shape[0])
        e = int(edge_src.shape[0])
        n_pad = pad_to_bucket(n)
        e_pad = pad_to_bucket(e)

        if sort_by_dst and e > 0:
            order = np.argsort(edge_dst, kind="stable")
            edge_src = edge_src[order]
            edge_dst = edge_dst[order]
            edge_type = edge_type[order]
            edge_feats = edge_feats[order]
            if edge_label is not None:
                edge_label = edge_label[order]

        nf = np.zeros((n_pad, node_feats.shape[1]), dtype=np.float32)
        nf[:n] = node_feats
        nt = np.zeros(n_pad, dtype=np.int32)
        nt[:n] = node_type

        es = np.zeros(e_pad, dtype=np.int32)
        ed = np.zeros(e_pad, dtype=np.int32)
        et = np.zeros(e_pad, dtype=np.int32)
        ef = np.zeros((e_pad, edge_feats.shape[1]), dtype=np.float32)
        es[:e] = edge_src
        ed[:e] = edge_dst
        et[:e] = edge_type
        ef[:e] = edge_feats

        uids = None
        if node_uids is not None:
            uids = np.zeros(n_pad, dtype=np.int32)
            uids[:n] = node_uids

        # pad-slot policy (pad dst → masked last node slot, pad src →
        # last real src) lives in from_presorted — one place only
        batch = GraphBatch.from_presorted(
            nf, nt, es, ed, et, ef, n, e,
            node_uids=uids,
            window_start_ms=window_start_ms,
            window_end_ms=window_end_ms,
        )
        if edge_label is not None:
            batch.edge_label[:e] = edge_label
        return batch
