"""Graph batching: resolved edges → static-shape COO snapshots.

This is the bridge between the streaming data plane and the device: a
DataStore sink (the BASELINE.json "new datastore.Backend behind the plugin
interface") that accumulates REQUEST_DTYPE edges into time windows and
closes each window into a padded, bucketed :class:`GraphBatch` ready for a
jit'd GNN — the role the COO batcher sidecar plays in SURVEY §2.1's
TPU-native plan.
"""

from alaz_tpu.graph.snapshot import GraphBatch, pad_to_bucket
from alaz_tpu.graph.builder import GraphBuilder, WindowedGraphStore

__all__ = ["GraphBatch", "pad_to_bucket", "GraphBuilder", "WindowedGraphStore"]
