"""Windowed edge → graph aggregation (the COO batcher).

``WindowedGraphStore`` implements the DataStore interface, making the GNN
scorer a drop-in sink behind the same plugin seam the reference exposes
(datastore/datastore.go:3-21): the aggregator persists REQUEST_DTYPE rows,
the store buckets them into fixed time windows, and each closed window
becomes a :class:`GraphBatch` (BASELINE.json: "batched into sparse COO
graphs ... behind the existing datastore.DataStore plugin interface").

Node identity is persistent across windows (uid → stable slot) so temporal
models see consistent node indexing; per-window features are recomputed
vectorized from that window's edges.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import numpy as np

from alaz_tpu.config import env_str
from alaz_tpu.datastore.interface import BaseDataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.k8s import EventType, ResourceType
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.obs.device import blocked_pad_waste_pct_from, pad_waste_pct_from
from alaz_tpu.obs.spans import SpanTracer

NODE_FEATURE_DIM = 32
EDGE_FEATURE_DIM = 16


class NodeTable:  # role-private: every instance is owned by one GraphBuilder and mutated only behind that builder's owner's lock (WindowedGraphStore._lock serial / ShardedIngest's bounded _merge_lock acquire sharded) — cross-role reach is serialized by the owner, and alazrace's golden map pins the ownership
    """uid-id → stable node slot, with endpoint type.

    Backed by flat int32 arrays, not a dict: uid ids are interner ids, so
    a uid-indexed slot array resolves a whole window's column in one
    vectorized take, and only genuinely-new uids cost any Python at all
    (one vectorized append per window, not one dict insert per uid). The
    slot array costs 4 bytes per interner id ever seen as a uid bound
    (amortized doubling) — the deliberate trade for O(1) row resolution;
    per-window transients are bounded by the window, not the id space
    (bulk_map's dense/sparse split).
    """

    def __init__(self) -> None:
        # uid id → slot, -1 = unseen (uids are dense interner ids)
        self._slot_of_uid = np.full(1024, -1, dtype=np.int32)
        self._uids = np.empty(1024, dtype=np.int32)
        self._types = np.empty(1024, dtype=np.int32)
        self._n = 0
        # batch-path instrumentation (perf smoke test: the vectorized
        # path must carry the traffic, not a per-row fallback)
        self.bulk_calls = 0
        self.scalar_calls = 0

    def __len__(self) -> int:
        return self._n

    def _ensure_uid_capacity(self, needed: int) -> None:
        cap = self._slot_of_uid.shape[0]
        if needed > cap:
            grown = np.full(max(needed, 2 * cap), -1, dtype=np.int32)
            grown[:cap] = self._slot_of_uid
            self._slot_of_uid = grown

    def _ensure_node_capacity(self, needed: int) -> None:
        cap = self._uids.shape[0]
        if needed > cap:
            new_cap = max(needed, 2 * cap)
            for name in ("_uids", "_types"):
                grown = np.empty(new_cap, dtype=np.int32)
                grown[: self._n] = getattr(self, name)[: self._n]
                setattr(self, name, grown)

    def get_or_add(self, uid_id: int, ep_type: int) -> int:
        self.scalar_calls += 1
        self._ensure_uid_capacity(uid_id + 1)
        slot = int(self._slot_of_uid[uid_id])
        if slot < 0:
            slot = self._n
            self._ensure_node_capacity(slot + 1)
            self._slot_of_uid[uid_id] = slot
            self._uids[slot] = uid_id
            self._types[slot] = ep_type
            self._n = slot + 1
        return slot

    def bulk_map(self, uid_ids: np.ndarray, ep_types: np.ndarray) -> np.ndarray:
        """get_or_add over a column of uid ids, fully vectorized AND
        sort-free: uids are dense interner ids, so presence comes from
        one bincount, first-occurrence indices from one reversed
        scatter, and after misses append (new slots in ascending-uid
        order — the exact order the scalar reference assigns them) every
        row resolves with a single take through the uid→slot array."""
        self.bulk_calls += 1
        uid_ids = np.asarray(uid_ids)
        n = uid_ids.shape[0]
        if n == 0:
            return np.zeros(0, dtype=np.int32)
        max_uid = int(uid_ids.max())
        self._ensure_uid_capacity(max_uid + 1)
        if max_uid < max(4 * n, 1 << 16):
            # dense id space: presence via bincount, first occurrence via
            # reversed scatter — no sort anywhere
            uniq = np.flatnonzero(np.bincount(uid_ids, minlength=max_uid + 1))
            miss = self._slot_of_uid[uniq] < 0
            if miss.any():
                miss_uids = uniq[miss].astype(np.int32)
                first_idx = np.empty(max_uid + 1, dtype=np.int64)
                first_idx[uid_ids[::-1]] = np.arange(n - 1, -1, -1)
                first_of_miss = first_idx[miss_uids]
                self._append_misses(miss_uids, np.asarray(ep_types)[first_of_miss])
        else:
            # sparse id space (the shared interner also numbers paths/SQL
            # strings, so uid ids can sit far above the window's node
            # count): one O(n log n) unique bounds the transients by the
            # WINDOW size, never by the global id space
            uniq, first_rows = np.unique(uid_ids, return_index=True)
            miss = self._slot_of_uid[uniq] < 0
            if miss.any():
                miss_uids = uniq[miss].astype(np.int32)
                self._append_misses(
                    miss_uids, np.asarray(ep_types)[first_rows[miss]]
                )
        return self._slot_of_uid[uid_ids]

    def _append_misses(self, miss_uids: np.ndarray, miss_types: np.ndarray) -> None:
        """Append new uids (ascending order — the scalar reference's slot
        assignment order) in one vectorized pass."""
        k = miss_uids.shape[0]
        self._ensure_node_capacity(self._n + k)
        self._uids[self._n : self._n + k] = miss_uids
        self._types[self._n : self._n + k] = miss_types
        self._slot_of_uid[miss_uids] = np.arange(
            self._n, self._n + k, dtype=np.int32
        )
        self._n += k

    def _scalar_bulk_map(self, uid_ids: np.ndarray, ep_types: np.ndarray) -> np.ndarray:
        """Pre-vectorization reference (one ``get_or_add`` per distinct
        uid, with per-element int() boxing) — kept for the equivalence
        property tests."""
        uniq, first_idx, inverse = np.unique(
            uid_ids, return_index=True, return_inverse=True
        )
        slots = np.empty(uniq.shape[0], dtype=np.int32)
        for j in range(uniq.shape[0]):
            slots[j] = self.get_or_add(int(uniq[j]), int(ep_types[first_idx[j]]))
        return slots[inverse]

    def types_array(self) -> np.ndarray:
        """Read-only view of the live types column (no per-call copy)."""
        return self._types[: self._n]

    def uids_array(self) -> np.ndarray:
        """Read-only view of the live uids column (no per-call copy)."""
        return self._uids[: self._n]


def cluster_renumber(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    n_nodes: int,
    edge_weight: np.ndarray | None = None,
) -> np.ndarray:
    """Locality-oriented node renumbering: a permutation ``perm`` with
    ``perm[old_id] = new_id`` that places sources talking to the same
    destination in one contiguous id range.

    Why: batches are dst-sorted (snapshot.py), so a window of consecutive
    edges shares few destinations; after this pass their *source* rows
    also live in a narrow band of the node table, turning the step's
    residual src-side gathers from random row hits into windowed reads
    (ARCHITECTURE.md §3b — the three ~9 ms src gathers are the remaining
    step-time bound, and uniform-random ids are their adversarial case).
    Real service maps have community structure (teams of pods calling
    their own services); this pass is what converts that structure into
    memory locality. Cost: one O(E log E) host-side sort per window —
    free next to the device step.

    Ordering key per node: (its modal destination, out-traffic desc,
    old id) — out-traffic is edge count when unweighted, total request
    weight otherwise. Nodes with no outgoing edges (services, sinks)
    keep their relative order after all sources. ``edge_weight`` weights
    both the modal vote and the tiebreak — essential on AGGREGATED
    graphs (one edge per (src,dst,proto) pair, GraphBuilder.build),
    where the per-edge request count is what distinguishes a pod's home
    service from a one-off noise pair."""
    if edge_src.shape[0] == 0:
        return np.arange(n_nodes, dtype=np.int32)
    # modal dst per src via (weighted) pair counting — vectorized groupby
    pair_key = edge_src.astype(np.int64) * np.int64(n_nodes) + edge_dst.astype(np.int64)
    uniq_pairs, inverse = np.unique(pair_key, return_inverse=True)
    if edge_weight is None:
        pair_counts = np.bincount(inverse, minlength=uniq_pairs.shape[0])
    else:
        pair_counts = np.bincount(
            inverse, weights=edge_weight.astype(np.float64),
            minlength=uniq_pairs.shape[0],
        )
    pair_src = (uniq_pairs // n_nodes).astype(np.int64)
    pair_dst = (uniq_pairs % n_nodes).astype(np.int64)
    # per src, pick the dst with max count: sort by (src, count) and take last
    order = np.lexsort((pair_counts, pair_src))
    boundaries = np.flatnonzero(np.diff(pair_src[order], append=-1))
    top_dst = np.full(n_nodes, np.int64(n_nodes), dtype=np.int64)  # sinks last
    if edge_weight is None:
        out_deg = np.bincount(edge_src, minlength=n_nodes).astype(np.float64)
    else:
        out_deg = np.bincount(
            edge_src, weights=edge_weight.astype(np.float64), minlength=n_nodes
        )
    top_dst[pair_src[order][boundaries]] = pair_dst[order][boundaries]
    new_order = np.lexsort((np.arange(n_nodes), -out_deg, top_dst))
    perm = np.empty(n_nodes, dtype=np.int32)
    perm[new_order] = np.arange(n_nodes, dtype=np.int32)
    return perm


def src_band_windows(
    edge_src: np.ndarray, tile: int | None = None, window: int | None = None
) -> float:
    """Mean number of ``window``-row node-table windows each ``tile``-edge
    chunk's src band spans — the banded gather kernel's exact cost model
    (DMAs/chunk). ~1-4 after cluster_renumber on community maps; ~N/128
    on uniform-random ids, where the XLA row gather is the right choice.
    Callers use this to pick ModelConfig.src_gather per deployment.
    Defaults come from ops.constants so the gauge can never drift from
    the kernel's actual tiling."""
    return src_locality_gauges(edge_src, n_nodes=0, tile=tile, window=window)[0]


def src_straggler_fraction(
    edge_src: np.ndarray,
    n_nodes: int,
    tile: int | None = None,
    window: int | None = None,
    band: int | None = None,
) -> float:
    """Fraction of edges whose src falls OUTSIDE the fixed
    ``band``-window band centered on its chunk's median window — the
    hybrid banded gather's exact fix-up cost model (the kernel covers the
    band; everything else is an XLA row op). ≲0.15 after
    cluster_renumber on ~90%-local community maps; →1.0 on
    uniform-random ids, where the plain XLA gather is the right choice.
    The kernel falls back to the plain gather above 1/8 (its static
    straggler budget), so the operator threshold is 0.125."""
    return src_locality_gauges(edge_src, n_nodes, tile=tile, window=window, band=band)[1]


def src_locality_gauges(
    edge_src: np.ndarray,
    n_nodes: int,
    tile: int | None = None,
    window: int | None = None,
    band: int | None = None,
) -> tuple[float, float]:
    """(mean band windows, straggler fraction) in one shared pass over
    ``edge_src`` — the per-window-close gauge pair shares the pad +
    reshape so the hot window-close path walks the array once.
    ``n_nodes`` ≤ 0 skips the straggler half (returns 1.0)."""
    from alaz_tpu.ops.constants import BAND_WINDOWS, DMA_WINDOW, TILE_E

    tile = TILE_E if tile is None else tile
    window = DMA_WINDOW if window is None else window
    band = BAND_WINDOWS if band is None else band
    e = edge_src.shape[0]
    if e == 0:
        return 0.0, 0.0
    pad = (-e) % tile
    ids = np.concatenate([edge_src, np.full(pad, edge_src[-1])]) if pad else edge_src
    win = ids.astype(np.int64) // window
    per_chunk = win.reshape(-1, tile)
    lo = per_chunk.min(axis=1)
    hi = per_chunk.max(axis=1)
    band_windows = float(np.mean(hi - lo + 1))
    if n_nodes <= 0:
        return band_windows, 1.0
    # ceil: the kernel sees the 128-padded node table, so a partial top
    # window is still coverable — flooring would misplace bands near the
    # table top and misread fractions sitting at the 0.125 threshold
    n_windows = max(1, -(-n_nodes // window))
    b = min(band, n_windows)
    med = np.median(per_chunk, axis=1).astype(np.int64)
    lo_w = np.clip(med - b // 2, 0, n_windows - b)
    lo_e = np.repeat(lo_w, tile)
    in_band = (win >= lo_e) & (win < lo_e + b)
    # padded ids replicate a real edge; count only the real edge axis
    return band_windows, float(np.mean(~in_band[:e]))


def apply_renumber(
    perm: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    *node_arrays: np.ndarray,
) -> tuple:
    """Apply a ``cluster_renumber`` permutation: edge endpoints are
    remapped through ``perm`` and every per-node array is reordered so
    row ``perm[i]`` of the output is row ``i`` of the input."""
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.shape[0], dtype=perm.dtype)
    out_nodes = tuple(a[inv] for a in node_arrays)
    return (perm[edge_src], perm[edge_dst]) + out_nodes


# ---------------------------------------------------------------------------
# Grouped reduction (the per-window argsort+reduceat stage). The numpy
# implementation below is the fallback; when libalaz_ingest.so is loaded
# the same reduction runs in C++ (native/ingest.cc alz_group_edges) —
# stateless, so shard workers call it concurrently. Both produce groups
# in ascending key order with bit-identical reductions for the
# integer-valued float64 columns the builder feeds.
# ---------------------------------------------------------------------------

_native_grouping: Optional[bool] = None  # None = auto-detect on first use


def set_native_grouping(enabled: Optional[bool]) -> None:
    """Force the grouping backend: True = C++ (raises later if the .so is
    missing — callers gate on native.available()), False = numpy,
    None = auto-detect (the default)."""
    global _native_grouping
    _native_grouping = enabled


def _use_native_grouping() -> bool:
    global _native_grouping
    if _native_grouping is None:
        try:
            from alaz_tpu.graph import native

            _native_grouping = native.available()
        except Exception:  # toolchain-less images: numpy serves
            _native_grouping = False
    return _native_grouping


def pack_group_key(
    src_slot: np.ndarray, dst_slot: np.ndarray, proto: np.ndarray
) -> np.ndarray:
    """DST-MAJOR (dst, src, proto) packing into one int64 sort key:
    ascending key order is dst-sorted (the layout GraphBatch needs), src
    keeps 28 bits (<2^28 slots), proto the low 4."""
    return (
        (dst_slot.astype(np.int64) << np.int64(32))
        | (src_slot.astype(np.int64) << np.int64(4))
        | (proto.astype(np.int64) & np.int64(0xF))
    )


def unpack_group_key(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(src_slot, dst_slot) halves of packed group keys. The protocol
    nibble is NOT recovered here — callers take it from a representative
    row so out-of-enum protocol bytes round-trip unclamped."""
    src = ((keys >> np.int64(4)) & np.int64(0xFFFFFFF)).astype(np.int32)
    dst = (keys >> np.int64(32)).astype(np.int32)
    return src, dst


def group_reduce(
    keys: np.ndarray,
    sum_cols: List[np.ndarray],
    max_cols: List[np.ndarray],
) -> tuple[np.ndarray, np.ndarray, np.ndarray, List[np.ndarray], List[np.ndarray]]:
    """Group rows by int64 key: ``(uniq_keys, count, rep, sums, maxes)``
    in ascending key order; ``rep`` is a representative row index per
    group. Routes through the C++ core when loaded; the numpy
    argsort+reduceat path is the fallback and the semantic reference."""
    n = keys.shape[0]
    if n and _use_native_grouping():
        from alaz_tpu.graph import native

        out = native.group_edges(keys, sum_cols, max_cols)
        if out is not None:
            return out
    if n == 0:
        empty = np.zeros(0, dtype=np.float64)
        return (
            np.zeros(0, dtype=np.int64), empty, np.zeros(0, dtype=np.int64),
            [empty.copy() for _ in sum_cols], [empty.copy() for _ in max_cols],
        )
    # ONE argsort serves grouping AND every per-group statistic: group
    # boundaries fall out of the sorted keys (what np.unique would have
    # argsorted a second time), per-group sum/max run as reduceat over
    # the sorted values. No stability requirement — any group member is
    # a valid representative. Group order is ascending key, np.unique's.
    order = np.argsort(keys)
    sk = keys[order]
    is_start = np.empty(n, dtype=bool)
    is_start[0] = True
    np.not_equal(sk[1:], sk[:-1], out=is_start[1:])
    starts = np.flatnonzero(is_start)
    count = (np.append(starts[1:], n) - starts).astype(np.float64)
    sums = [np.add.reduceat(c[order], starts) for c in sum_cols]
    maxes = [np.maximum.reduceat(c[order], starts) for c in max_cols]
    return sk[starts], count, order[starts], sums, maxes


# ---------------------------------------------------------------------------
# Degree-capped neighbor sampling (ISSUE 7). One dst with in-degree ~N (a
# hot-key service) turns a window's aggregated edge list into an N-row
# batch: the bucket ladder jumps to its top rung and the close wave pays
# an N-proportional assembly. The cap bounds per-dst fan-in at window
# close with DETERMINISTIC reservoir sampling — every edge draws a 64-bit
# priority that is a pure function of (seed, window, dst-uid, src-uid,
# proto), and each over-cap dst keeps the `cap` smallest (bottom-k ==
# uniform reservoir sample under hash-random priorities, the
# sample-and-aggregate GNN sampling form, PAPERS.md). Purity is the
# point: serial builds, N-worker merges and reruns all select the same
# edges, so the sharded equivalence contract survives the cap.
#
# The selection routes through the C++ core (alz_sample_degree_cap,
# operating on the already-dst-grouped edges alz_group_edges emits) when
# the .so is loaded — same toggle as the grouping backend
# (set_native_grouping) so parity tests A/B both with one switch; the
# numpy lexsort path below is the fallback and the semantic reference.
# Ties break by ascending row index in BOTH backends (numpy's stable
# lexsort == the C++ (prio, idx) comparator), so they are bit-identical.
# ---------------------------------------------------------------------------

_MIX_C1 = 0xFF51AFD7ED558CCD  # splitmix64 finalizer constants — mirrored
_MIX_C2 = 0xC4CEB9FE1A85EC53  # by mix64() in native/ingest.cc (alazspec-pinned)
_U64 = np.uint64
_MASK64 = (1 << 64) - 1


def _mix64(x: np.ndarray) -> np.ndarray:
    """Vectorized mix64 — the same finalizer native/ingest.cc uses for
    its hash probes; uint64 arithmetic wraps mod 2^64 on both sides."""
    x = x.astype(np.uint64, copy=True)
    x ^= x >> _U64(33)
    x *= _U64(_MIX_C1)
    x ^= x >> _U64(33)
    x *= _U64(_MIX_C2)
    x ^= x >> _U64(33)
    return x


def _mix64_int(x: int) -> int:
    """Scalar mix64 over Python ints (avoids numpy scalar overflow
    warnings when mixing the (seed, window) base)."""
    x &= _MASK64
    x ^= x >> 33
    x = (x * _MIX_C1) & _MASK64
    x ^= x >> 33
    x = (x * _MIX_C2) & _MASK64
    x ^= x >> 33
    return x


def sample_priorities(
    seed: int,
    window_start_ms: int,
    dst_uid: np.ndarray,
    src_uid: np.ndarray,
    proto: np.ndarray,
) -> np.ndarray:
    """Per-edge sampling priority: a pure function of (seed, window,
    dst-uid, src-uid, proto) — uids, not slots, so any pipeline that
    interns the same strings draws the same sample regardless of worker
    count or slot-assignment order."""
    base = _mix64_int((int(seed) << 32) ^ (int(window_start_ms) & _MASK64))
    x = (
        (dst_uid.astype(np.int64).astype(np.uint64) << _U64(32))
        ^ src_uid.astype(np.int64).astype(np.uint64)
        ^ (proto.astype(np.int64).astype(np.uint64) << _U64(56))
    )
    x ^= _U64(base)
    return _mix64(x)


def degree_cap_select(
    e_dst: np.ndarray, prio: np.ndarray, cap: int
) -> np.ndarray:
    """Indices (ascending) of the edges that survive the per-dst cap:
    for every dst group in the DST-SORTED edge list, the ``cap``
    smallest priorities (ties by row index). C++ when loaded, numpy
    lexsort fallback otherwise — bit-identical by construction."""
    n = e_dst.shape[0]
    if n and _use_native_grouping():
        from alaz_tpu.graph import native

        out = native.sample_degree_cap(e_dst, prio, cap)
        if out is not None:
            return out
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    # stable lexsort: within a dst group, ascending (prio, original
    # index) — the exact order the C++ (prio, idx) comparator ranks
    order = np.lexsort((prio, e_dst))
    sd = e_dst[order]
    is_start = np.empty(n, dtype=bool)
    is_start[0] = True
    np.not_equal(sd[1:], sd[:-1], out=is_start[1:])
    starts = np.flatnonzero(is_start)
    sizes = np.diff(np.append(starts, n))
    rank = np.arange(n, dtype=np.int64) - np.repeat(starts, sizes)
    keep = order[rank < cap]
    keep.sort()
    return keep


@dataclass
class EdgeAggregate:
    """One window's aggregated edges, slot-keyed — what feature assembly
    consumes. Produced either directly from REQUEST rows
    (GraphBuilder.build) or by recombining shard-worker partials
    (GraphBuilder.build_from_partials)."""

    e_src: np.ndarray  # [E] int32 node slots
    e_dst: np.ndarray  # [E] int32
    e_type: np.ndarray  # [E] int32 protocol codes
    count: np.ndarray  # [E] float64 (integer-valued)
    lat_sum: np.ndarray  # [E] float64
    lat_max: np.ndarray  # [E] float64
    err5_sum: np.ndarray  # [E] float64
    err4_sum: np.ndarray  # [E] float64
    tls_sum: np.ndarray  # [E] float64
    label_sum: Optional[np.ndarray] = None  # [E] float64

    @property
    def n_edges(self) -> int:
        return int(self.e_src.shape[0])


@dataclass
class EdgePartial:
    """A shard worker's per-(window, chunk) partial aggregation, keyed by
    UID (not slot: workers must not touch the shared NodeTable — slot
    assignment happens once, in the merge stage). All reductions are
    integer-valued float64, so merge-order changes cannot perturb them;
    the merge recombines same-key partial edges with one reduceat pass."""

    from_uid: np.ndarray  # [P] int32
    to_uid: np.ndarray  # [P] int32
    from_type: np.ndarray  # [P]
    to_type: np.ndarray  # [P]
    proto: np.ndarray  # [P] int32
    count: np.ndarray  # [P] float64
    lat_sum: np.ndarray  # [P] float64
    lat_max: np.ndarray  # [P] float64
    err5_sum: np.ndarray  # [P] float64
    err4_sum: np.ndarray  # [P] float64
    tls_sum: np.ndarray  # [P] float64
    label_sum: Optional[np.ndarray]  # [P] float64
    rows: int  # raw REQUEST rows folded in (conservation accounting)


def _request_row_stats(rows: np.ndarray) -> tuple[np.ndarray, ...]:
    """The per-row reduction inputs every aggregation path shares:
    (lat, err5, err4, tls) as float64 columns of a REQUEST batch."""
    lat = rows["latency_ns"].astype(np.float64)
    status = rows["status_code"].astype(np.int64)
    err5 = ((status >= 500) | (~rows["completed"])).astype(np.float64)
    err4 = ((status >= 400) & (status < 500)).astype(np.float64)
    tls = rows["tls"].astype(np.float64)
    return lat, err5, err4, tls


def aggregate_rows(
    rows: np.ndarray,
    src_slot: np.ndarray,
    dst_slot: np.ndarray,
    edge_label: Optional[np.ndarray] = None,
) -> tuple[EdgeAggregate, np.ndarray]:
    """REQUEST rows + slot columns → (EdgeAggregate, rep) via one grouped
    reduction over the dst-major key."""
    proto = rows["protocol"]
    key = pack_group_key(src_slot, dst_slot, proto.astype(np.int64))
    lat, err5, err4, tls = _request_row_stats(rows)
    sum_cols = [lat, err5, err4, tls]
    if edge_label is not None:
        sum_cols.append(edge_label.astype(np.float64))
    uniq, count, rep, sums, maxes = group_reduce(key, sum_cols, [lat])
    e_src, e_dst = unpack_group_key(uniq)
    agg = EdgeAggregate(
        e_src=e_src,
        e_dst=e_dst,
        e_type=proto[rep].astype(np.int32),
        count=count,
        lat_sum=sums[0],
        lat_max=maxes[0],
        err5_sum=sums[1],
        err4_sum=sums[2],
        tls_sum=sums[3],
        label_sum=sums[4] if edge_label is not None else None,
    )
    return agg, rep


def partial_from_rows(
    rows: np.ndarray,
    local_nodes: NodeTable,
    edge_label: Optional[np.ndarray] = None,
) -> EdgePartial:
    """A shard worker's thread-local aggregation of one chunk's window
    rows: grouping runs against the worker's PRIVATE NodeTable (slots are
    only a grouping aid here — the output is uid-keyed), so no shared
    state is touched and workers aggregate fully in parallel."""
    local_src = local_nodes.bulk_map(rows["from_uid"], rows["from_type"])
    local_dst = local_nodes.bulk_map(rows["to_uid"], rows["to_type"])
    agg, rep = aggregate_rows(rows, local_src, local_dst, edge_label)
    return EdgePartial(
        from_uid=rows["from_uid"][rep].astype(np.int32),
        to_uid=rows["to_uid"][rep].astype(np.int32),
        from_type=rows["from_type"][rep],
        to_type=rows["to_type"][rep],
        proto=agg.e_type,
        count=agg.count,
        lat_sum=agg.lat_sum,
        lat_max=agg.lat_max,
        err5_sum=agg.err5_sum,
        err4_sum=agg.err4_sum,
        tls_sum=agg.tls_sum,
        label_sum=agg.label_sum,
        rows=int(rows.shape[0]),
    )


class GraphBuilder:  # role-private: every instance is owned by one store and its mutations (node table growth, pad/sample counters) run only behind that owner's lock (WindowedGraphStore._lock serial / ShardedIngest's bounded _merge_lock acquire sharded) — cross-role reach is serialized by the owner, and alazrace's golden map pins the ownership
    """Aggregates one window's REQUEST_DTYPE rows into a GraphBatch.

    ``renumber=True`` applies the cluster_renumber locality pass to each
    built batch: node rows/ids are permuted per window so co-communicating
    sources are contiguous (narrow src bands → the banded gather kernel).
    The permutation is self-consistent within the batch (features, types,
    uids, and edge endpoints all move together; score export reads uids
    through the permuted table) but node SLOTS then differ between
    windows — do not combine with models that carry per-slot state across
    windows (the temporal model's memory)."""

    def __init__(
        self,
        nodes: Optional[NodeTable] = None,
        window_s: float = 1.0,
        renumber: bool = False,
        degree_cap: int = 0,
        sample_seed: int = 0,
        ledger=None,
        tracer: Optional[SpanTracer] = None,
        edge_layout: Optional[str] = None,
    ):
        self.nodes = nodes if nodes is not None else NodeTable()
        self.window_s = window_s
        self.renumber = renumber
        # edge-buffer layout this builder emits (ISSUE 20): "blocked"
        # computes the per-128-dst-row extents eagerly at window close
        # (assembly is the host's staging decision — the scoring thread
        # must never pay the searchsorted) and feeds the block-slot
        # ledger. Defaults from EDGE_LAYOUT so every construction site
        # (service, bench, sharded merge, replay) honors the env switch
        # without threading a parameter through each one.
        self.edge_layout = (
            edge_layout if edge_layout is not None
            else env_str("EDGE_LAYOUT", "coo")
        )
        # per-dst fan-in bound at window close (0 = unlimited — the
        # bit-identical legacy path). Sampled-away edges attribute their
        # request rows to the ledger's closed `sampled` cause.
        self.degree_cap = int(degree_cap)
        self.sample_seed = int(sample_seed)
        self.ledger = ledger
        # span plane (ISSUE 9): the builder owns three stages of the
        # window lifecycle — `merge` (grouped reduction/recombine),
        # `assemble` (feature matrices + pad/bucket) and `sample` (the
        # degree-cap decision + selection). None = untraced (training,
        # standalone builds) at zero cost.
        self.tracer = tracer
        self.sampled_rows = 0  # request rows cut by the cap (cumulative)
        self.sampled_edges = 0  # aggregated edges cut by the cap
        # bucket capacity accounting (ISSUE 11): every assembled batch
        # splits its edge bucket into real vs pad slots, so host-only
        # pipelines (bench --ingest, the chaos harness) publish the same
        # pad_waste_pct the service's staging-side device plane gauges —
        # assembly IS the host's staging decision, the device just pays
        # for it
        self.assembled_edge_rows = 0  # real (masked-in) edge slots
        self.assembled_pad_slots = 0  # pad-tail slots shipped anyway
        self.assembled_block_slots = 0  # blocked-layout tile slots

    @property
    def pad_waste_pct(self) -> float:
        """Percentage of assembled edge slots that were pad, cumulative
        over every batch this builder emitted — the host-side twin of
        the device plane's gauge, computed through the ONE shared
        definition (obs/device.py pad_waste_pct_from)."""
        return pad_waste_pct_from(
            self.assembled_edge_rows, self.assembled_pad_slots
        )

    @property
    def block_fill_pct(self) -> float:
        """Fill percentage of the blocked layout's tile slots, cumulative
        over every blocked batch — the host-side twin of the device
        plane's ``device.block_fill_pct`` gauge, through the same shared
        definition (obs/device.py blocked_pad_waste_pct_from). 0.0 until
        a blocked batch was assembled (COO builders never feed it)."""
        if not self.assembled_block_slots:
            return 0.0
        return 100.0 - blocked_pad_waste_pct_from(
            self.assembled_edge_rows, self.assembled_block_slots
        )

    def build(
        self,
        rows: np.ndarray,
        window_start_ms: int = 0,
        window_end_ms: int = 0,
        edge_label: Optional[np.ndarray] = None,
    ) -> GraphBatch:
        """Vectorized groupby (from_uid, to_uid, protocol) → edge rows with
        count/latency/error/tls features; node features from incident edges.

        ``edge_label`` is per-request labels (fault injection ground truth);
        an aggregated edge is labeled 1 if any of its requests were faulty.
        """
        tr = self.tracer
        t0 = time.perf_counter() if tr is not None else 0.0
        src_slot = self.nodes.bulk_map(rows["from_uid"], rows["from_type"])
        dst_slot = self.nodes.bulk_map(rows["to_uid"], rows["to_type"])
        # DST-MAJOR key → grouped reduction (C++ when loaded, numpy
        # argsort+reduceat otherwise): the aggregated edge list arrives
        # already dst-sorted, so assembly skips the per-window stable sort
        agg, _ = aggregate_rows(rows, src_slot, dst_slot, edge_label)
        if tr is not None:
            tr.observe(window_start_ms, "merge", time.perf_counter() - t0)
        return self._assemble(agg, window_start_ms, window_end_ms)

    def build_from_partials(
        self,
        partials: List[EdgePartial],
        window_start_ms: int = 0,
        window_end_ms: int = 0,
    ) -> GraphBatch:
        """Merge shard-worker partials into the window's GraphBatch: map
        uids through the SHARED NodeTable (miss slots append in
        ascending-uid order — the same assignment the single-thread path
        makes for the same window row set), then recombine same-key
        partial edges with one grouped-reduction pass (sum for
        count/lat/err/tls/label, max for lat_max). Bit-identical to
        ``build`` over the concatenated rows while per-window latency
        sums stay integer-exact in float64 (< 2^53 ns ≈ 104 days)."""
        tr = self.tracer
        t0 = time.perf_counter() if tr is not None else 0.0
        from_uid = np.concatenate([p.from_uid for p in partials])
        to_uid = np.concatenate([p.to_uid for p in partials])
        from_type = np.concatenate([p.from_type for p in partials])
        to_type = np.concatenate([p.to_type for p in partials])
        proto = np.concatenate([p.proto for p in partials])
        src_slot = self.nodes.bulk_map(from_uid, from_type)
        dst_slot = self.nodes.bulk_map(to_uid, to_type)
        key = pack_group_key(src_slot, dst_slot, proto.astype(np.int64))
        has_label = bool(partials) and all(
            p.label_sum is not None for p in partials
        )
        sum_cols = [
            np.concatenate([p.count for p in partials]),
            np.concatenate([p.lat_sum for p in partials]),
            np.concatenate([p.err5_sum for p in partials]),
            np.concatenate([p.err4_sum for p in partials]),
            np.concatenate([p.tls_sum for p in partials]),
        ]
        if has_label:
            sum_cols.append(np.concatenate([p.label_sum for p in partials]))
        max_cols = [np.concatenate([p.lat_max for p in partials])]
        uniq, _, rep, sums, maxes = group_reduce(key, sum_cols, max_cols)
        e_src, e_dst = unpack_group_key(uniq)
        agg = EdgeAggregate(
            e_src=e_src,
            e_dst=e_dst,
            e_type=proto[rep].astype(np.int32),
            count=sums[0],
            lat_sum=sums[1],
            lat_max=maxes[0],
            err5_sum=sums[2],
            err4_sum=sums[3],
            tls_sum=sums[4],
            label_sum=sums[5] if has_label else None,
        )
        if tr is not None:
            tr.observe(window_start_ms, "merge", time.perf_counter() - t0)
        return self._assemble(agg, window_start_ms, window_end_ms)

    def _assemble(
        self, agg: EdgeAggregate, window_start_ms: int, window_end_ms: int
    ) -> GraphBatch:
        """EdgeAggregate → GraphBatch: edge/node feature matrices, the
        optional locality renumber, pad/bucket. The ONE feature-assembly
        definition the direct and sharded-merge paths share — two copies
        could drift."""
        tr = self.tracer
        t0 = time.perf_counter() if tr is not None else 0.0
        n_edges = agg.n_edges
        e_src, e_dst, e_type = agg.e_src, agg.e_dst, agg.e_type
        count = agg.count
        lat_sum, lat_max = agg.lat_sum, agg.lat_max
        err5_sum, err4_sum, tls_sum = agg.err5_sum, agg.err4_sum, agg.tls_sum
        label_sum = agg.label_sum

        # -- node features ---------------------------------------------------
        # Everything here derives from the EDGE aggregates (sums of sums
        # of the per-row stats — exact, the inputs are integer-valued),
        # so the sharded merge needs no row-level columns. Computed from
        # the FULL aggregate, BEFORE any degree-cap sampling: the host
        # knows the true totals, so a hot-key dst keeps its real
        # in-degree/in-count/in-error signal even when its edge list is
        # cut — the anomaly stays visible while the batch stays bounded.
        n_nodes = len(self.nodes)
        node_type = self.nodes.types_array()
        nf = np.zeros((n_nodes, NODE_FEATURE_DIM), dtype=np.float32)
        for t in range(4):
            nf[:, t] = node_type == t
        out_cnt = np.bincount(e_src, weights=count, minlength=n_nodes)
        in_cnt = np.bincount(e_dst, weights=count, minlength=n_nodes)
        out_err = np.bincount(e_src, weights=err5_sum, minlength=n_nodes)
        in_err = np.bincount(e_dst, weights=err5_sum, minlength=n_nodes)
        out_lat = np.bincount(e_src, weights=lat_sum, minlength=n_nodes)
        in_lat = np.bincount(e_dst, weights=lat_sum, minlength=n_nodes)
        out_deg = np.bincount(e_src, minlength=n_nodes).astype(np.float64)
        in_deg = np.bincount(e_dst, minlength=n_nodes).astype(np.float64)
        nf[:, 4] = np.log1p(out_cnt)
        nf[:, 5] = np.log1p(in_cnt)
        nf[:, 6] = out_err / np.maximum(out_cnt, 1.0)
        nf[:, 7] = in_err / np.maximum(in_cnt, 1.0)
        nf[:, 8] = np.log1p(out_lat / np.maximum(out_cnt, 1.0)) / 20.0
        nf[:, 9] = np.log1p(in_lat / np.maximum(in_cnt, 1.0)) / 20.0
        nf[:, 10] = np.log1p(out_deg)
        nf[:, 11] = np.log1p(in_deg)

        # -- degree-capped sampling (ISSUE 7) --------------------------------
        # n_edges <= cap is a free sufficient no-op check; past it, one
        # O(E) bincount decides whether any dst actually exceeds the cap
        # (the steady-state service map never does — this path costs one
        # bincount until the day a hot key shows up). The `sample` span
        # stage times this whole block — with no cap it measures the
        # decision branch, so the stage is nonzero in EVERY pipeline and
        # the span-completeness gate needs no cap conditional.
        ts0 = time.perf_counter() if tr is not None else 0.0
        if 0 < self.degree_cap < n_edges and int(in_deg.max()) > self.degree_cap:
            uids = self.nodes.uids_array()
            prio = sample_priorities(
                self.sample_seed, window_start_ms,
                uids[e_dst], uids[e_src], e_type,
            )
            keep = degree_cap_select(e_dst, prio, self.degree_cap)
            if keep.shape[0] < n_edges:
                cut_edges = n_edges - int(keep.shape[0])
                total_rows = int(round(float(count.sum())))
                e_src, e_dst, e_type = e_src[keep], e_dst[keep], e_type[keep]
                count = count[keep]
                lat_sum, lat_max = lat_sum[keep], lat_max[keep]
                err5_sum, err4_sum = err5_sum[keep], err4_sum[keep]
                tls_sum = tls_sum[keep]
                if label_sum is not None:
                    label_sum = label_sum[keep]
                cut_rows = total_rows - int(round(float(count.sum())))
                n_edges = int(keep.shape[0])
                self.sampled_edges += cut_edges
                self.sampled_rows += cut_rows
                if self.ledger is not None:
                    self.ledger.add("sampled", cut_rows, reason="degree_cap")
        sample_s = (time.perf_counter() - ts0) if tr is not None else 0.0

        window_s = max(self.window_s, 1e-6)
        mean_lat = lat_sum / np.maximum(count, 1.0)
        ef = np.zeros((n_edges, EDGE_FEATURE_DIM), dtype=np.float32)
        ef[:, 0] = np.log1p(count)
        ef[:, 1] = np.log1p(mean_lat) / 20.0
        ef[:, 2] = np.log1p(lat_max) / 20.0
        ef[:, 3] = err5_sum / np.maximum(count, 1.0)
        ef[:, 4] = err4_sum / np.maximum(count, 1.0)
        ef[:, 5] = tls_sum / np.maximum(count, 1.0)
        ef[:, 6] = np.log1p(count / window_s)
        # slots 7..15: protocol one-hot. Folding the edge-type embedding
        # into the edge features lets models learn type offsets through
        # their edge-feature projection instead of a per-edge embedding
        # gather — a [1M]-row gather costs ~9ms/step on TPU (row-op bound)
        # while these host-side writes are free.
        proto_idx = np.clip(e_type, 0, 8)
        ef[np.arange(n_edges), 7 + proto_idx] = 1.0

        el = None
        if label_sum is not None:
            el = (label_sum > 0).astype(np.float32)

        node_uids = self.nodes.uids_array()
        if self.renumber and n_edges > 0:
            # weight the modal vote by request count: heavy home-service
            # traffic must outrank one-off noise pairs on aggregated edges
            perm = cluster_renumber(e_src, e_dst, n_nodes, edge_weight=count)
            e_src, e_dst, nf, node_type, node_uids = apply_renumber(
                perm, e_src, e_dst, nf, node_type, node_uids
            )

        batch = GraphBatch.build(
            node_feats=nf,
            node_type=node_type,
            edge_src=e_src,
            edge_dst=e_dst,
            edge_type=e_type,
            edge_feats=ef,
            edge_label=el,
            node_uids=node_uids,
            window_start_ms=window_start_ms,
            window_end_ms=window_end_ms,
            # already dst-sorted by the dst-major group key (the
            # renumber path remaps endpoints, so its edges must re-sort)
            sort_by_dst=self.renumber and n_edges > 0,
        )
        self.assembled_edge_rows += batch.n_edges
        self.assembled_pad_slots += batch.pad_edge_slots
        if self.edge_layout == "blocked":
            # eager extent fill AT CLOSE: block_starts caches into the
            # batch, so staging/scoring consume the window invariant
            # without recomputing the searchsorted, and the telemetry
            # plane reads `edge_block_starts is not None` as the
            # blocked-window signal (obs/device.py observe_staged)
            batch.block_starts()
            self.assembled_block_slots += batch.blocked_edge_slots
        if tr is not None:
            tr.observe(window_start_ms, "sample", sample_s)
            tr.observe(
                window_start_ms, "assemble",
                (time.perf_counter() - t0) - sample_s,
            )
        return batch


class WindowedGraphStore(BaseDataStore):
    """DataStore sink: buckets persisted requests into time windows and
    emits a GraphBatch per closed window via ``on_batch`` (or an internal
    list). Windows close when a request arrives ≥1 window past their end
    (watermark), or on ``flush()``."""

    def __init__(
        self,
        interner: Interner,
        window_s: float = 1.0,
        on_batch: Optional[Callable[[GraphBatch], None]] = None,
        label_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        renumber: bool = False,
        ledger=None,
        degree_cap: int = 0,
        sample_seed: int = 0,
        tracer: Optional[SpanTracer] = None,
        edge_layout: Optional[str] = None,
    ):
        self.interner = interner
        self.window_s = window_s
        self.window_ms = int(window_s * 1000)
        self.on_batch = on_batch
        self.label_fn = label_fn
        # shared DropLedger (ISSUE 6): late stragglers attribute there in
        # addition to the store-local counter; degree-cap cuts (ISSUE 7)
        # attribute through the builder as `sampled`
        self.ledger = ledger
        # window-lifecycle span plane (ISSUE 9): ON by default — a store
        # with no caller-supplied tracer keeps a private one whose spans
        # complete at emit (no scorer behind it). The service passes its
        # metrics-registered tracer instead, which stays open through
        # score/export. Cost is per window×stage, never per row.
        if tracer is None:
            tracer = SpanTracer(complete_at_emit=True)
        self.tracer = tracer
        self.builder = GraphBuilder(
            window_s=window_s, renumber=renumber,
            degree_cap=degree_cap, sample_seed=sample_seed, ledger=ledger,
            tracer=tracer, edge_layout=edge_layout,
        )
        self.batches: List[GraphBatch] = []
        self.request_count = 0  # guarded-by: self._lock
        self.late_dropped = 0  # guarded-by: self._lock
        self.last_persist_monotonic: float | None = None  # guarded-by: self._lock
        self._pending: dict[int, List[np.ndarray]] = {}
        self._watermark = -1  # guarded-by: self._lock
        self._closed_upto = -1
        self._lock = threading.Lock()

    # -- DataStore surface -------------------------------------------------

    def persist_requests(self, batch: np.ndarray) -> None:
        with self._lock:
            self.last_persist_monotonic = time.monotonic()
            self.request_count += batch.shape[0]
            if batch.shape[0] == 0:
                return
            wids = batch["start_time_ms"] // self.window_ms
            wmin, wmax = int(wids.min()), int(wids.max())
            if wmin == wmax:
                # the dominant steady-state shape: a whole chunk inside
                # one window — no sort, no per-window masking. Copy: the
                # rows are retained across calls and the caller may
                # reuse its buffer.
                present: np.ndarray | List[int] = [wmin]
            elif wmax - wmin < (1 << 20):
                # ascending like np.unique, but via one O(n) presence
                # bincount instead of a sort
                present = np.flatnonzero(np.bincount(wids - wmin)) + wmin
            else:  # degenerate timestamps: don't size a bincount by span
                present = np.unique(wids)
            for w in present:
                w = int(w)
                if w <= self._closed_upto:
                    # stragglers for an already-emitted window (e.g. the
                    # aggregator's retry path): drop, never re-emit a
                    # window — and never pay the row copy for them
                    k = batch.shape[0] if wmin == wmax else int((wids == w).sum())
                    self.late_dropped += k
                    if self.ledger is not None:
                        self.ledger.add("late", k)
                    continue
                rows = batch.copy() if wmin == wmax else batch[wids == w]
                self._pending.setdefault(w, []).append(rows)
                # span origin: idempotent, first call per window wins
                # (lock order: store lock → tracer lock, one direction)
                self.tracer.first_row(w * self.window_ms)
                if w > self._watermark:
                    self._watermark = w
            self._close_upto(self._watermark - 1)

    def persist_kafka_events(self, batch: np.ndarray) -> None:
        pass  # kafka edges already flow through persist_requests in topology terms

    def persist_alive_connections(self, batch: np.ndarray) -> None:
        pass

    def persist_resource(self, rtype: ResourceType, event: EventType, obj: Any) -> None:
        pass  # node metadata arrives via the aggregator's cluster state

    # -- window lifecycle --------------------------------------------------

    def _close_upto(self, upto: int) -> None:
        done = [w for w in self._pending if w <= upto]
        if done:
            self._closed_upto = max(self._closed_upto, max(done))
        for w in sorted(done):
            ws_ms = w * self.window_ms
            # the close reached this window: open-window residency since
            # first_row becomes the `scatter` stage
            self.tracer.close_start(ws_ms)
            tc0 = time.perf_counter()
            parts = self._pending.pop(w)
            rows = np.concatenate(parts) if len(parts) > 1 else parts[0]
            labels = self.label_fn(rows) if self.label_fn is not None else None
            # serial path: the pop+concat+label step is this window's
            # whole per-shard close (one shard — the store itself)
            self.tracer.observe(ws_ms, "shard_close", time.perf_counter() - tc0)
            batch = self.builder.build(
                rows,
                window_start_ms=ws_ms,
                window_end_ms=(w + 1) * self.window_ms,
                edge_label=labels,
            )
            if self.on_batch is not None:
                self.on_batch(batch)
            else:
                self.batches.append(batch)  # alazlint: disable=ALZ050 -- every close path appends under _lock (ingest/flush callers); scenario replay's batches read is a single-threaded epilogue after flush() returns
            self.tracer.emit(ws_ms)

    def flush(self) -> None:
        with self._lock:
            self._close_upto(self._watermark)
