"""CLI driver for the chaos suite — what ``make chaos`` runs.

One JSON line per seed; exit 1 if any seed produced findings. The
default seeds are the fixed acceptance set: every PR must keep them
finding-free (wired into ``make test``).
"""

from __future__ import annotations

import argparse
import json
import sys

from alaz_tpu.chaos.harness import run_chaos_suite
from alaz_tpu.config import ChaosConfig


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m alaz_tpu.chaos",
        description="run the chaos suite (all four seams) over fixed seeds",
    )
    p.add_argument("--seeds", type=int, nargs="+", default=[0, 1, 2])
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--rows", type=int, default=48_000)
    p.add_argument(
        "--legs", nargs="+", default=["pipeline", "frames", "backend"],
        choices=["pipeline", "frames", "backend"],
    )
    from alaz_tpu.replay.incidents import SCENARIO_NAMES

    p.add_argument(
        "--composed", default="hot_key", metavar="SCENARIO",
        choices=list(SCENARIO_NAMES) + ["none"],
        help="also run one scenario×chaos composition (ISSUE 7): the "
        "named incident scenario's host leg with the chaos seams armed "
        "on top — 'hot-key during a degraded delivery'. 'none' skips it",
    )
    p.add_argument(
        "--tenants", action="store_true",
        help="also run the two-tenant incident+worker-kill composition "
        "(ISSUE 14): K=2 tenants, sharded partitions, hot_key incident "
        "AND chaos crashes on ONE tenant's pool — per-tenant ledger "
        "conservation must hold EXACTLY through the kills, and the "
        "clean tenant's latency/drift gates stay ON",
    )
    args = p.parse_args(argv)

    failed = 0
    for seed in args.seeds:
        cfg = ChaosConfig(enabled=True, seed=seed)
        rep = run_chaos_suite(
            cfg,
            n_workers=args.workers,
            n_rows=args.rows,
            legs=tuple(args.legs),
        )
        print(json.dumps(rep.as_dict(), sort_keys=True))
        if not rep.ok:
            failed += 1
    if args.composed and args.composed != "none":
        from alaz_tpu.replay.incidents import run_incident_scenario

        srep = run_incident_scenario(
            args.composed,
            seed=args.seeds[0],
            n_workers=args.workers,
            detection=False,
            chaos=ChaosConfig(enabled=True, seed=args.seeds[0]),
        )
        print(json.dumps(srep.as_dict(), sort_keys=True))
        if not srep.ok:
            failed += 1
    if args.tenants:
        from alaz_tpu.replay.tenants import run_isolation_scenario

        trep = run_isolation_scenario(
            tenants=2,
            seed=args.seeds[0],
            incident="hot_key",
            ingest_workers=args.workers,
            # paced (default): the clean tenant's latency/drift gates
            # stay ON — incident + chaos on one fleet must not move the
            # other (the ISSUE 14 acceptance combination); kills arm
            # only on the perturbed tenant's pool
            chaos=ChaosConfig(
                enabled=True,
                seed=args.seeds[0],
                # boosted crash pressure: the composition exists to
                # prove conservation THROUGH kills, so make them likely
                worker_crash_prob=0.05,
                worker_max_crashes=4,
            ),
        )
        print(json.dumps(trep.as_dict(), sort_keys=True))
        if not trep.ok:
            failed += 1
    if failed:
        print(f"# {failed} seed(s) with findings", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
