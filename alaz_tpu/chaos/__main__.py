"""CLI driver for the chaos suite — what ``make chaos`` runs.

One JSON line per seed; exit 1 if any seed produced findings. The
default seeds are the fixed acceptance set: every PR must keep them
finding-free (wired into ``make test``).
"""

from __future__ import annotations

import argparse
import json
import sys

from alaz_tpu.chaos.harness import run_chaos_suite
from alaz_tpu.config import ChaosConfig


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m alaz_tpu.chaos",
        description="run the chaos suite (all four seams) over fixed seeds",
    )
    p.add_argument("--seeds", type=int, nargs="+", default=[0, 1, 2])
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--rows", type=int, default=48_000)
    p.add_argument(
        "--legs", nargs="+", default=["pipeline", "frames", "backend"],
        choices=["pipeline", "frames", "backend"],
    )
    p.add_argument(
        "--ingest-backend", default="thread",
        choices=["thread", "process", "both"],
        help="sharded-ingest backend for the pipeline leg (ISSUE 15): "
        "'process' SIGKILLs real shard processes mid-wave instead of "
        "raising in threads; 'both' runs the thread suite as usual and "
        "then a process-mode pipeline leg per seed (same conservation/"
        "monotonic/self-healing gates through the kills)",
    )
    from alaz_tpu.replay.incidents import SCENARIO_NAMES

    p.add_argument(
        "--composed", default="hot_key", metavar="SCENARIO",
        choices=list(SCENARIO_NAMES) + ["none"],
        help="also run one scenario×chaos composition (ISSUE 7): the "
        "named incident scenario's host leg with the chaos seams armed "
        "on top — 'hot-key during a degraded delivery'. 'none' skips it",
    )
    p.add_argument(
        "--tenants", action="store_true",
        help="also run the two-tenant incident+worker-kill composition "
        "(ISSUE 14): K=2 tenants, sharded partitions, hot_key incident "
        "AND chaos crashes on ONE tenant's pool — per-tenant ledger "
        "conservation must hold EXACTLY through the kills, and the "
        "clean tenant's latency/drift gates stay ON",
    )
    args = p.parse_args(argv)

    failed = 0
    first_backend = (
        "process" if args.ingest_backend == "process" else "thread"
    )
    for seed in args.seeds:
        cfg = ChaosConfig(enabled=True, seed=seed)
        rep = run_chaos_suite(
            cfg,
            n_workers=args.workers,
            n_rows=args.rows,
            legs=tuple(args.legs),
            ingest_backend=first_backend,
        )
        print(json.dumps(rep.as_dict(), sort_keys=True))
        if not rep.ok:
            failed += 1
    if args.ingest_backend == "both" and "pipeline" in args.legs:
        # process-mode pipeline leg per seed (ISSUE 15): the same
        # worker-seam faults land as SIGKILLs on real shard processes;
        # the conservation/monotonic/self-healing gates must hold
        # through the kill (frames/backend legs are backend-independent
        # and already ran above)
        for seed in args.seeds:
            cfg = ChaosConfig(enabled=True, seed=seed)
            rep = run_chaos_suite(
                cfg,
                n_workers=args.workers,
                n_rows=args.rows,
                legs=("pipeline",),
                ingest_backend="process",
            )
            print(json.dumps(rep.as_dict(), sort_keys=True))
            if not rep.ok:
                failed += 1
    if args.composed and args.composed != "none":
        from alaz_tpu.replay.incidents import run_incident_scenario

        srep = run_incident_scenario(
            args.composed,
            seed=args.seeds[0],
            n_workers=args.workers,
            detection=False,
            chaos=ChaosConfig(enabled=True, seed=args.seeds[0]),
        )
        print(json.dumps(srep.as_dict(), sort_keys=True))
        if not srep.ok:
            failed += 1
    if args.tenants:
        from alaz_tpu.replay.tenants import run_isolation_scenario

        trep = run_isolation_scenario(
            tenants=2,
            seed=args.seeds[0],
            incident="hot_key",
            ingest_workers=args.workers,
            # paced (default): the clean tenant's latency/drift gates
            # stay ON — incident + chaos on one fleet must not move the
            # other (the ISSUE 14 acceptance combination); kills arm
            # only on the perturbed tenant's pool
            chaos=ChaosConfig(
                enabled=True,
                seed=args.seeds[0],
                # boosted crash pressure: the composition exists to
                # prove conservation THROUGH kills, so make them likely
                worker_crash_prob=0.05,
                worker_max_crashes=4,
            ),
        )
        print(json.dumps(trep.as_dict(), sort_keys=True))
        if not trep.ok:
            failed += 1
    if failed:
        print(f"# {failed} seed(s) with findings", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
