"""The chaos suite: drive the REAL pipeline through all four fault
seams and check invariants, not vibes.

Three legs, each wrapping production code with an injector from
``chaos.injectors`` (nothing under test is mocked):

1. **pipeline** — ``ShardedIngest`` fed a perturbed delivery (duplicated
   / reordered / late batches) with crash+stall injection on the worker
   threads and bounded-block shedding on the scatter. Gates:
   - *bounded*: ``flush``/``drain`` return within their timeouts with
     workers killed mid-run (the supervisor restarts them);
   - *conservation*: delivered rows == emitted rows + drop-ledger total
     — EXACT (semantic aggregator drops ride the ledger's ``filtered``
     cause since ISSUE 8, and must equal the stats counters);
   - *monotonic*: emitted windows strictly ascend; duplicate delivery
     never re-emits a window;
   - *self-healing*: injected crashes imply observed restarts.
2. **frames** — a real ``IngestServer`` on a loopback socket fed
   chaos-mutated wire frames over ONE connection. Gates: the connection
   survives corruption (resync), every clean frame's rows arrive, and
   accepted == sent − destroyed (exact when truncation is off — the
   default — because only header/count corruption is then in play and
   neither can eat a neighboring frame).
3. **backend** — a ``BatchingBackend`` over a ``FlakyTransport``
   (5xx + timeouts) on a fake clock. Gates: every appended row settles
   as sent or failed (no row stuck or double-counted), the breaker
   opens under sustained failure, and it closes again after ``heal()``.

``run_chaos_suite`` returns a :class:`ChaosReport`; ``findings`` empty
means every gate held. ``python -m alaz_tpu.chaos`` (= ``make chaos``)
sweeps fixed seeds and exits nonzero on any finding; ``bench.py
--ingest`` runs a short suite every round and reports
``chaos_findings`` (expected 0) next to ``ingest_rows_per_sec``.
"""

from __future__ import annotations

import dataclasses
import socket
import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from alaz_tpu.aggregator.cluster import ClusterInfo
from alaz_tpu.aggregator.sharded import ShardedIngest
from alaz_tpu.chaos.injectors import (
    BatchChaos,
    FlakyTransport,
    FrameChaos,
    WorkerChaos,
    WorkerCrash,
)
from alaz_tpu.config import BackendConfig, ChaosConfig
from alaz_tpu.events.intern import Interner
from alaz_tpu.logging import get_logger
from alaz_tpu.obs.device import batch_pad_waste_pct
from alaz_tpu.obs.recorder import FlightRecorder
from alaz_tpu.replay.synth import make_ingest_trace
from alaz_tpu.utils.ledger import DropLedger

log = get_logger("alaz_tpu.chaos")


@dataclass
class ChaosReport:
    seed: int
    n_workers: int
    findings: List[str] = field(default_factory=list)
    pipeline: dict = field(default_factory=dict)
    frames: dict = field(default_factory=dict)
    backend: dict = field(default_factory=dict)
    # flight-recorder trail (ISSUE 9): attached by run_chaos_suite when
    # any gate failed — the last-N structured events (chaos injections,
    # worker crashes/restarts, ledger decisions, window spans) so the
    # failure replays as a story instead of a bare assertion
    recorder_dump: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        out = {
            "seed": self.seed,
            "n_workers": self.n_workers,
            "chaos_findings": len(self.findings),
            "findings": self.findings,
            "pipeline": self.pipeline,
            "frames": self.frames,
            "backend": self.backend,
        }
        if self.recorder_dump is not None:
            out["recorder_dump"] = self.recorder_dump
        return out


def emitted_rows(batches) -> int:
    """Rows aggregated into emitted GraphBatches — the shared
    ``GraphBatch.aggregated_rows`` inverse-log1p measure, summed (the
    sanitize suite's accounting trick)."""
    return sum(b.aggregated_rows() for b in batches)


def _run_pipeline_leg(
    cfg: ChaosConfig,
    n_workers: int,
    n_rows: int,
    n_windows: int,
    findings: List[str],
    recorder: Optional[FlightRecorder] = None,
    backend: str = "thread",
) -> dict:
    ev, msgs = make_ingest_trace(
        n_rows, pods=60, svcs=10, windows=n_windows, seed=cfg.seed
    )
    interner = Interner()
    ledger = DropLedger()
    closed: List = []
    wchaos = WorkerChaos(
        seed=cfg.seed,
        crash_prob=cfg.worker_crash_prob,
        stall_prob=cfg.worker_stall_prob,
        stall_s=cfg.worker_stall_s,
        max_crashes=cfg.worker_max_crashes,
        ensure_crash=True,  # ≥1 mid-wave kill per run, never vacuous
    )
    bchaos = BatchChaos(
        seed=cfg.seed + 1,
        dup_prob=cfg.batch_dup_prob,
        reorder_prob=cfg.batch_reorder_prob,
        late_prob=cfg.batch_late_prob,
        min_each=True,  # every enabled delivery fault fires ≥ once
    )
    chunk = max(2048, n_rows // 32)
    chunks = [ev[i : i + chunk] for i in range(0, n_rows, chunk)]
    delivery, late = bchaos.perturb(chunks)
    fault_hook = wchaos
    if recorder is not None:
        # delivery-seam injections land in the trail once, as a summary
        recorder.record(
            "chaos_inject", seam="batch",
            duplicated=bchaos.duplicated, reordered=bchaos.reordered,
            late=bchaos.delayed,
        )

        def fault_hook(i: int, kind: str) -> None:
            # worker-seam injections: record only when the injector
            # actually fired ON THIS CALL (the hook runs at every item
            # boundary). Attribution comes from the raise/return, never
            # from diffing wchaos's shared totals — concurrent workers
            # racing between a peer's read and its increment would
            # record phantom/duplicate injections
            try:
                effect = wchaos(i, kind)
            except WorkerCrash:
                recorder.record(
                    "chaos_inject", seam="worker", worker=i,
                    item_kind=kind, effect="crash",
                )
                raise
            if effect is not None:
                recorder.record(
                    "chaos_inject", seam="worker", worker=i,
                    item_kind=kind, effect=effect,
                )

    if backend == "process":
        # process-mode pipeline (ISSUE 15): SAME seams, SAME gates. The
        # worker seam's WorkerCrash verdicts become SIGKILLs of real
        # shard processes mid-wave — conservation must hold through a
        # kill that freezes the worker's books mid-flight. Topology goes
        # through process_k8s (the ring broadcast): a pre-folded shared
        # ClusterInfo cannot cross the spawn boundary.
        from alaz_tpu.shm.process_pool import ProcessShardedIngest

        pipe = ProcessShardedIngest(
            n_workers,
            interner=interner,
            window_s=1.0,
            on_batch=closed.append,
            ledger=ledger,
            fault_hook=fault_hook,
            shed_block_s=0.5,
            recorder=recorder,
        )
        for m in msgs:
            pipe.process_k8s(m)
    else:
        cluster = ClusterInfo(interner)
        for m in msgs:
            cluster.handle_msg(m)
        pipe = ShardedIngest(
            n_workers,
            interner=interner,
            cluster=cluster,
            window_s=1.0,
            on_batch=closed.append,
            ledger=ledger,
            fault_hook=fault_hook,
            shed_block_s=0.5,
            recorder=recorder,
        )
    t0 = time.perf_counter()
    try:
        for c in delivery:
            pipe.process_l7(c, now_ns=10_000_000_000)
        tf = time.perf_counter()
        if not pipe.flush(timeout_s=30.0):
            findings.append("pipeline: flush #1 did not complete in 30s")
        flush_wall = time.perf_counter() - tf
        if flush_wall > 35.0:
            findings.append(
                f"pipeline: flush #1 overran its timeout ({flush_wall:.1f}s)"
            )
        # partial agent outage replay: the held-back batches arrive after
        # the horizon sealed — every row must drop as LATE, none vanish
        for c in late:
            pipe.process_l7(c, now_ns=10_000_000_000)
        if not pipe.flush(timeout_s=30.0):
            findings.append("pipeline: flush #2 did not complete in 30s")
        td = time.perf_counter()
        if not pipe.drain(timeout_s=10.0):
            findings.append("pipeline: drain did not settle in 10s")
        drain_wall = time.perf_counter() - td
        if drain_wall > 12.0:
            findings.append(
                f"pipeline: drain overran its timeout ({drain_wall:.1f}s)"
            )
        wall = time.perf_counter() - t0
    finally:
        pipe.stop()

    delivered = int(sum(c.shape[0] for c in delivery)) + int(
        sum(c.shape[0] for c in late)
    )
    emitted = emitted_rows(closed)
    stats = pipe.stats.as_dict()
    # semantic drops are ledgered as `filtered` now (ISSUE 8): the gate
    # is exactly delivered == emitted + ledger.total, and the stats
    # counters must agree with the ledgered cause (both gates below)
    semantic = (
        stats["l7_dropped_no_socket"]
        + stats["l7_dropped_not_pod"]
        + stats["l7_rate_limited"]
    )
    gap = ledger.conservation_gap(delivered, emitted)
    if gap != 0:
        findings.append(
            f"pipeline: row conservation broken — delivered={delivered} "
            f"emitted={emitted} semantic={semantic} "
            f"ledger={ledger.snapshot()} gap={gap}"
        )
    if ledger.count("filtered") != semantic:
        findings.append(
            f"pipeline: filtered-ledger drift — stats say {semantic} "
            f"semantic drops, ledger says {ledger.count('filtered')}"
        )
    starts = [b.window_start_ms for b in closed]
    if any(b <= a for a, b in zip(starts, starts[1:])):
        findings.append(
            "pipeline: window emission not strictly ascending "
            f"(duplicate or reordered emit): {starts}"
        )
    if wchaos.crashes > 0 and pipe.worker_restarts == 0:
        findings.append(
            f"pipeline: {wchaos.crashes} crashes injected but no worker restart observed"
        )
    if late and ledger.count("late") == 0:
        findings.append(
            "pipeline: late delivery injected but nothing ledgered as late"
        )
    return {
        "backend": backend,
        "delivered_rows": delivered,
        "emitted_rows": emitted,
        "windows": len(closed),
        "rows_per_sec": round(delivered / wall) if wall > 0 else 0,
        "flush_wall_s": round(flush_wall, 3),
        # bucket-padding waste over the degraded run (ISSUE 11): chaos
        # fragments windows (dup/reorder/late redelivery), which shows
        # up here as occupancy loss — the number rides the report so a
        # defense that "passes" by emitting near-empty buckets is visible
        "pad_waste_pct": round(batch_pad_waste_pct(closed), 2),
        "ledger": ledger.snapshot(),
        "worker_restarts": pipe.worker_restarts,
        "crashes": wchaos.crashes,
        "stalls": wchaos.stalls,
        "duplicated_batches": bchaos.duplicated,
        "reordered_batches": bchaos.reordered,
        "late_batches": bchaos.delayed,
    }


class _CountingSink:
    """Minimal service duck-type for the frame leg: counts submitted
    rows; no pipeline behind it (the pipeline leg covers that)."""

    graph_store = None
    metrics = None

    def __init__(self, ledger: DropLedger):
        self.ledger = ledger
        self.rows = 0

    def submit_l7(self, batch, tenant: int = 0) -> bool:
        self.rows += int(batch.shape[0])
        return True

    def submit_tcp(self, batch, tenant: int = 0) -> bool:
        return True

    def submit_proc(self, batch, tenant: int = 0) -> bool:
        return True


def _run_frame_leg(
    cfg: ChaosConfig,
    findings: List[str],
    recorder: Optional[FlightRecorder] = None,
) -> dict:
    from alaz_tpu.sources.ingest_server import KIND_L7, IngestServer, pack_frame

    n_frames, rows_per_frame = 48, 256
    ev, _ = make_ingest_trace(
        n_frames * rows_per_frame, pods=20, svcs=4, windows=2, seed=cfg.seed
    )
    fchaos = FrameChaos(
        seed=cfg.seed + 2,
        corrupt_prob=cfg.frame_corrupt_prob,
        truncate_prob=cfg.frame_truncate_prob,
        garble_prob=cfg.frame_garble_prob,
        min_each=True,
        expect_frames=n_frames,
    )
    ledger = DropLedger()
    # quarantine decisions land in the suite ring (the ledger hook):
    # a failing frame gate ships the per-frame drop trail with it
    ledger.recorder = recorder
    sink = _CountingSink(ledger)
    server = IngestServer(sink, port=0)
    server.start()
    try:
        wire = b"".join(
            fchaos.perturb(
                pack_frame(KIND_L7, ev[k * rows_per_frame : (k + 1) * rows_per_frame]),
                rows_per_frame,
            )
            for k in range(n_frames)
        )
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.connect(server.address)
        try:
            s.sendall(wire)
        finally:
            s.close()
        # one connection carried everything; wait for the serve thread to
        # drain it (EOF after the last byte)
        deadline = time.monotonic() + 10.0
        sent_rows = n_frames * rows_per_frame
        expect = sent_rows - fchaos.destroyed_rows
        while time.monotonic() < deadline and sink.rows < expect:
            time.sleep(0.02)
    finally:
        server.stop()

    mutated = fchaos.corrupted + fchaos.garbled + fchaos.truncated
    if fchaos.truncate_prob == 0.0:
        # exact contract: header/count corruption destroys only its own
        # frame — every clean frame survives the resyncs around it
        if sink.rows != expect:
            findings.append(
                f"frames: accepted {sink.rows} rows, expected {expect} "
                f"(sent {sent_rows}, destroyed {fchaos.destroyed_rows})"
            )
    elif sink.rows > expect:
        findings.append(
            f"frames: accepted {sink.rows} rows > conservable {expect}"
        )
    if mutated and server.quarantined_frames == 0:
        findings.append(
            f"frames: {mutated} frames mutated but none quarantined"
        )
    if fchaos.corrupted and server.resyncs == 0:
        findings.append(
            f"frames: {fchaos.corrupted} headers corrupted but no resync ran"
        )
    return {
        "frames_sent": n_frames,
        "rows_sent": sent_rows,
        "rows_accepted": sink.rows,
        "destroyed_rows": fchaos.destroyed_rows,
        "corrupted": fchaos.corrupted,
        "garbled": fchaos.garbled,
        "truncated": fchaos.truncated,
        "quarantined_frames": server.quarantined_frames,
        "resyncs": server.resyncs,
        "resync_bytes": server.resync_bytes,
        "ledger": ledger.snapshot(),
    }


def _run_backend_leg(
    cfg: ChaosConfig,
    findings: List[str],
    recorder: Optional[FlightRecorder] = None,
) -> dict:
    from alaz_tpu.datastore.backend import BatchingBackend
    from alaz_tpu.datastore.dto import make_requests

    clock = [0.0]

    def time_fn() -> float:
        return clock[0]

    def sleep_fn(s: float) -> None:
        clock[0] += s

    calls = [0]

    def ok_transport(endpoint, payload) -> int:
        calls[0] += 1
        return 200

    flaky = FlakyTransport(
        ok_transport,
        seed=cfg.seed + 3,
        error_prob=cfg.backend_error_prob,
        timeout_prob=cfg.backend_timeout_prob,
    )
    ledger = DropLedger()
    be = BatchingBackend(
        flaky,
        Interner(),
        BackendConfig(
            batch_size=40,
            max_retries=1,
            backoff_min_s=0.05,
            backoff_max_s=0.2,
            breaker_threshold=3,
            breaker_cooldown_s=5.0,
        ),
        time_fn=time_fn,
        sleep_fn=sleep_fn,
        ledger=ledger,
    )
    # breaker open/close flips land in the suite ring, so a failing
    # backend gate replays WHEN the export leg went dark
    be.breaker.recorder = recorder
    appended = 0
    # phase 1 — DEGRADED: cfg-intensity flapping (some sends fail, some
    # land; the breaker may or may not trip — either is legal here)
    for _ in range(6):
        be.persist_requests(make_requests(40))
        appended += 40
        be.pump(force=True)
        sleep_fn(0.5)
    # phase 2 — OUTAGE: the backend goes fully dark; the breaker MUST
    # open within threshold sends and then short the rest (the failure
    # cost becomes a counter bump, not retries×backoff per batch)
    flaky.error_prob, flaky.timeout_prob = 1.0, 0.0
    for _ in range(6):
        be.persist_requests(make_requests(40))
        appended += 40
        be.pump(force=True)
        sleep_fn(0.5)
    if be.breaker.opens == 0:
        findings.append("backend: full outage never opened the circuit breaker")
    if be.breaker.shorted == 0:
        findings.append("backend: open breaker never short-circuited a send")
    # phase 3 — RECOVERY: faults off, cooldown elapses; the half-open
    # probe must close the circuit and deliveries must resume
    flaky.heal()
    sleep_fn(6.0)
    be.persist_requests(make_requests(40))
    appended += 40
    be.pump(force=True)
    st = be.stats()
    req = st["requests"]
    # EXACT conservation through the export leg (ISSUE 12 satellite):
    # every appended row is sent, still pending, failed on the wire, or
    # shed by the open breaker — and every shed row is attributed to the
    # drop ledger's closed `shed` cause, exactly once. The old gate let
    # breaker sheds hide inside `stream.failed`; now the ledger is the
    # bookkeeper the rest of the pipeline already answers to.
    settled = req["sent"] + req["failed"] + req["shed"]
    if settled + req["pending"] != appended:
        findings.append(
            f"backend: rows unaccounted — appended={appended} "
            f"sent={req['sent']} failed={req['failed']} "
            f"shed={req['shed']} pending={req['pending']}"
        )
    shed_ledgered = ledger.count("shed")
    if shed_ledgered != req["shed"]:
        findings.append(
            f"backend: ledger drift — stream shed {req['shed']} rows but "
            f"the ledger holds {shed_ledgered} under `shed` (every "
            "breaker short must attribute exactly once)"
        )
    if be.breaker.state != "closed":
        findings.append(
            f"backend: breaker stuck {be.breaker.state} after recovery"
        )
    return {
        "appended_rows": appended,
        "sent": req["sent"],
        "failed": req["failed"],
        "shed": req["shed"],
        "ledger_shed": shed_ledgered,
        "breaker_opens": be.breaker.opens,
        "breaker_shorted": be.breaker.shorted,
        "breaker_state": be.breaker.state,
        "transport_errors": flaky.errors,
        "transport_timeouts": flaky.timeouts,
    }


def run_chaos_suite(
    cfg: Optional[ChaosConfig] = None,
    *,
    seed: Optional[int] = None,
    n_workers: int = 2,
    n_rows: int = 48_000,
    n_windows: int = 5,
    legs: tuple = ("pipeline", "frames", "backend"),
    ingest_backend: str = "thread",
) -> ChaosReport:
    """One full chaos run at ``cfg`` intensity (default intensities with
    ``seed`` when only a seed is given). Deterministic per (cfg, seed)
    up to thread interleaving; the GATES hold for every interleaving.

    ``cfg.enabled`` is honored: a disabled config zeroes every
    intensity, so the same gates run over a CLEAN pipeline — conservation
    with an all-zero ledger (what the no-chaos bench ride-along checks)."""
    if cfg is None:
        cfg = ChaosConfig(enabled=True, seed=seed if seed is not None else 0)
    elif seed is not None:
        # never mutate the caller's config object (it may be the live
        # service's config.chaos, whose seed a soak consumer reads later)
        cfg = dataclasses.replace(cfg, seed=seed)
    if not cfg.enabled:
        cfg = ChaosConfig(
            seed=cfg.seed,
            frame_corrupt_prob=0.0, frame_truncate_prob=0.0,
            frame_garble_prob=0.0,
            batch_dup_prob=0.0, batch_reorder_prob=0.0, batch_late_prob=0.0,
            worker_crash_prob=0.0, worker_stall_prob=0.0,
            backend_error_prob=0.0, backend_timeout_prob=0.0,
        )
    report = ChaosReport(seed=cfg.seed, n_workers=n_workers)
    # the suite's flight recorder (ISSUE 9): chaos injections, worker
    # crashes/restarts, ledger decisions and window spans all land in
    # one ring; a failing gate ships the trail WITH the report
    recorder = FlightRecorder(capacity=1024)
    if "pipeline" in legs:
        report.pipeline = _run_pipeline_leg(
            cfg, n_workers, n_rows, n_windows, report.findings,
            recorder=recorder, backend=ingest_backend,
        )
    if "frames" in legs:
        report.frames = _run_frame_leg(cfg, report.findings, recorder=recorder)
    if "backend" in legs:
        report.backend = _run_backend_leg(cfg, report.findings, recorder=recorder)
    if report.findings:
        report.recorder_dump = recorder.dump()
        log.warning(
            "chaos gates failed — flight recorder trail: "
            f"{recorder.tail_summary(last=64)}"
        )
    for f in report.findings:
        log.warning(f"chaos finding: {f}")
    return report
