"""Seeded fault injectors for the four pipeline seams.

Each injector wraps ONE seam of the real pipeline — no mocks of the
thing under test, only of the failure source:

- :class:`FrameChaos`    — the agent→socket wire (sources/ingest_server):
                           corrupt headers, truncated payloads, garbled
                           count fields.
- :class:`BatchChaos`    — the delivery plane between a source and the
                           ingestion surface: duplicated, reordered and
                           late batches (partial agent outage).
- :class:`WorkerChaos`   — the shard worker threads (aggregator/sharded):
                           crashes and stalls at item boundaries.
- :class:`FlakyTransport`— the backend datastore (datastore/backend):
                           5xx bursts and timeouts.

Everything is seed-driven (numpy Generator per injector, split
per-worker where threads are involved) so a chaos run is reproducible:
the same seed draws the same faults, modulo thread interleaving for the
worker seam (the INJECTION decisions are deterministic per worker; which
wall-clock instant they land at is the scheduler's).
"""

from __future__ import annotations

import struct
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

# the crash contract lives with the worker pool (the seam owner): the
# supervisor catches exactly this type, so there must be ONE class
from alaz_tpu.aggregator.sharded import WorkerCrash

__all__ = [
    "WorkerCrash",
    "WorkerChaos",
    "BatchChaos",
    "FrameChaos",
    "FlakyTransport",
]


class WorkerChaos:
    """``fault_hook`` for :class:`~alaz_tpu.aggregator.sharded.ShardedIngest`.

    Called at item boundaries as ``hook(worker_idx, kind)``; may raise
    :class:`WorkerCrash` (the thread dies; the pipeline attributes the
    in-flight rows and the supervisor restarts it) or sleep (a stalled
    worker). Crash/stall draws are per-worker seeded streams, so worker
    i's fault sequence is a pure function of (seed, i, its item count).

    ``max_crashes`` bounds the total kills (shared across workers) so a
    high ``crash_prob`` can't degenerate into an infinite restart storm;
    ``kinds`` selects which item kinds are at risk — ("close",) aims
    every kill mid-wave, the hardest case for the merge plane.
    ``ensure_crash`` guarantees the suite is never vacuous: if the
    random draws produced no kill by the first close item, that close
    dies — every run exercises a mid-wave kill + restart.
    """

    def __init__(
        self,
        seed: int = 0,
        crash_prob: float = 0.0,
        stall_prob: float = 0.0,
        stall_s: float = 0.02,
        max_crashes: Optional[int] = 4,
        kinds: Sequence[str] = ("l7", "tcp", "close"),
        ensure_crash: bool = False,
    ):
        self.seed = int(seed)
        self.crash_prob = float(crash_prob)
        self.stall_prob = float(stall_prob)
        self.stall_s = float(stall_s)
        self.max_crashes = max_crashes
        self.kinds = tuple(kinds)
        self.ensure_crash = bool(ensure_crash) and self.crash_prob > 0
        self.crashes = 0  # guarded-by: self._lock
        self.stalls = 0  # guarded-by: self._lock
        self._rngs: dict = {}  # worker idx -> Generator  # guarded-by: self._lock
        self._lock = threading.Lock()

    def _draw(self, worker: int) -> Tuple[float, float]:
        with self._lock:
            rng = self._rngs.get(worker)
            if rng is None:
                rng = np.random.default_rng((self.seed, worker))
                self._rngs[worker] = rng
            return float(rng.random()), float(rng.random())

    def __call__(self, worker: int, kind: str) -> Optional[str]:
        """Returns ``"stall"`` when THIS call stalled, ``None`` otherwise;
        a crash raises :class:`WorkerCrash`. Per-call attribution lives in
        the return/raise — callers must not diff the shared ``crashes``/
        ``stalls`` totals, which race across concurrent workers."""
        if kind not in self.kinds:
            return None
        r_crash, r_stall = self._draw(worker)
        crash = r_crash < self.crash_prob
        if not crash and self.ensure_crash and kind == "close":
            # coverage floor: the random draws spared every item so far —
            # kill this close (mid-wave, the hardest restart case)
            with self._lock:
                crash = self.crashes == 0
        if crash:
            with self._lock:
                capped = (
                    self.max_crashes is not None
                    and self.crashes >= self.max_crashes
                )
                if not capped:
                    self.crashes += 1
            if not capped:
                raise WorkerCrash(f"chaos kill: worker {worker} on {kind}")
        if r_stall < self.stall_prob:
            with self._lock:
                self.stalls += 1
            time.sleep(self.stall_s)
            return "stall"
        return None


class BatchChaos:
    """Delivery-plane chaos: duplicate, reorder and delay batches.

    ``perturb(chunks)`` is a PURE function of (seed, chunks): it returns
    ``(delivery, late)`` where ``delivery`` is the in-band sequence
    (with duplicates inserted and adjacent swaps applied) and ``late``
    are the held-back batches to deliver after the consumer has sealed
    its window horizon (a flush) — the deterministic replication of a
    partial agent outage re-sending its buffer after the backend moved
    on. Feeding the SAME perturbed sequence to two pipelines makes
    equivalence testable: the chaos is in the data, not the clock.

    ``min_each`` floors the coverage: every enabled fault kind fires at
    least once per perturb even when the random draws spared every batch
    (duplicate the middle, swap the first adjacent pair, hold the last
    batch late) — an acceptance run must never be vacuously green.
    """

    def __init__(
        self,
        seed: int = 0,
        dup_prob: float = 0.05,
        reorder_prob: float = 0.05,
        late_prob: float = 0.0,
        min_each: bool = False,
    ):
        self.rng = np.random.default_rng(seed)
        self.dup_prob = float(dup_prob)
        self.reorder_prob = float(reorder_prob)
        self.late_prob = float(late_prob)
        self.min_each = bool(min_each)
        self.duplicated = 0
        self.reordered = 0
        self.delayed = 0
        self.duplicated_rows = 0
        self.delayed_rows = 0

    def perturb(self, chunks: Sequence) -> Tuple[List, List]:
        out: List = []
        late: List = []
        for c in chunks:
            if self.late_prob and float(self.rng.random()) < self.late_prob:
                late.append(c)
                self.delayed += 1
                self.delayed_rows += len(c)
                continue
            out.append(c)
            if self.dup_prob and float(self.rng.random()) < self.dup_prob:
                out.append(c)
                self.duplicated += 1
                self.duplicated_rows += len(c)
        if self.min_each and out:
            if self.late_prob and not late:
                late.append(out.pop())
                self.delayed += 1
                self.delayed_rows += len(late[-1])
            if self.dup_prob and not self.duplicated and out:
                mid = len(out) // 2
                out.insert(mid + 1, out[mid])
                self.duplicated += 1
                self.duplicated_rows += len(out[mid])
        if self.reorder_prob:
            # adjacent swaps over disjoint pairs: each batch moves at most
            # one slot, so a window spread over several chunks keeps at
            # least one in-order carrier (the window-set invariant)
            i = 0
            while i + 1 < len(out):
                if float(self.rng.random()) < self.reorder_prob:
                    out[i], out[i + 1] = out[i + 1], out[i]
                    self.reordered += 1
                    i += 2
                else:
                    i += 1
            if self.min_each and not self.reordered and len(out) > 1:
                out[0], out[1] = out[1], out[0]
                self.reordered += 1
        return out, late


_MAGIC_LE = struct.Struct("<I")


class FrameChaos:
    """Wire-frame chaos for the socket seam.

    ``perturb(frame, rows)`` takes one packed frame (header + payload)
    and either passes it through or mutates it: header corruption
    (magic garbled — the stream must RESYNC), payload truncation (the
    framing desynchronizes mid-payload), or a count-field garble (the
    header stays framed but the payload no longer matches — the frame
    quarantines without losing stream sync). Destroyed row counts are
    tracked injector-side (``destroyed_rows``) because a frame whose
    header is gone carries no readable count for the server to ledger.

    ``min_each`` floors coverage like BatchChaos: with random draws that
    spared everything, the frames at 1/3 and 2/3 of ``expect_frames``
    get a forced corrupt/garble so every suite run drives a real resync.
    """

    def __init__(
        self,
        seed: int = 0,
        corrupt_prob: float = 0.05,
        truncate_prob: float = 0.0,
        garble_prob: float = 0.05,
        min_each: bool = False,
        expect_frames: int = 0,
    ):
        self.rng = np.random.default_rng(seed)
        self.corrupt_prob = float(corrupt_prob)
        self.truncate_prob = float(truncate_prob)
        self.garble_prob = float(garble_prob)
        self.min_each = bool(min_each)
        self.expect_frames = int(expect_frames)
        self._seen = 0
        self.corrupted = 0
        self.truncated = 0
        self.garbled = 0
        self.destroyed_rows = 0

    def perturb(self, frame: bytes, rows: int) -> bytes:
        self._seen += 1
        if self.min_each and self.expect_frames:
            if (
                self.corrupt_prob
                and not self.corrupted
                and self._seen == self.expect_frames // 3
            ):
                self.corrupted += 1
                self.destroyed_rows += rows
                return b"\xde\xad\xbe\xef" + frame[4:]
            if (
                self.garble_prob
                and not self.garbled
                and self._seen == (2 * self.expect_frames) // 3
            ):
                self.garbled += 1
                self.destroyed_rows += rows
                count = struct.unpack_from("<I", frame, 8)[0]
                out = bytearray(frame)
                struct.pack_into("<I", out, 8, count + 1)
                return bytes(out)
        r = float(self.rng.random())
        if r < self.corrupt_prob:
            # garble the magic: the receiver loses framing and must scan
            self.corrupted += 1
            self.destroyed_rows += rows
            return b"\xde\xad\xbe\xef" + frame[4:]
        r -= self.corrupt_prob
        if r < self.truncate_prob and len(frame) > 24:
            # drop the payload tail: the next header read lands mid-frame
            self.truncated += 1
            self.destroyed_rows += rows
            cut = int(self.rng.integers(16, len(frame) - 4))
            return frame[:cut]
        r -= self.truncate_prob
        if r < self.garble_prob:
            # count field no longer matches length: well-framed, malformed
            self.garbled += 1
            self.destroyed_rows += rows
            count = struct.unpack_from("<I", frame, 8)[0]
            out = bytearray(frame)
            struct.pack_into("<I", out, 8, count + 1)
            return bytes(out)
        return frame


class FlakyTransport:
    """Backend chaos: wrap a ``Transport`` with seeded 5xx and timeouts.

    Thread-safe (the backend pump and forced flushes may race). Faults
    can be turned off mid-run (``heal()``) to exercise circuit-breaker
    recovery."""

    def __init__(
        self,
        inner,
        seed: int = 0,
        error_prob: float = 0.1,
        timeout_prob: float = 0.05,
        error_status: int = 503,
    ):
        self.inner = inner
        self.error_prob = float(error_prob)
        self.timeout_prob = float(timeout_prob)
        self.error_status = int(error_status)
        self._rng = np.random.default_rng(seed)  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.calls = 0  # guarded-by: self._lock
        self.errors = 0  # guarded-by: self._lock
        self.timeouts = 0  # guarded-by: self._lock
        self.delivered = 0  # guarded-by: self._lock

    def heal(self) -> None:
        """Stop injecting: the backend 'recovers'."""
        self.error_prob = 0.0
        self.timeout_prob = 0.0

    def __call__(self, endpoint: str, payload: dict) -> int:
        with self._lock:
            self.calls += 1
            r_t, r_e = float(self._rng.random()), float(self._rng.random())
            if r_t < self.timeout_prob:
                self.timeouts += 1
                fate = "timeout"
            elif r_e < self.error_prob:
                self.errors += 1
                fate = "error"
            else:
                self.delivered += 1
                fate = "ok"
        if fate == "timeout":
            raise TimeoutError("chaos: backend timeout")
        if fate == "error":
            return self.error_status
        return self.inner(endpoint, payload)
