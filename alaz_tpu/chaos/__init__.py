"""alaz_tpu.chaos — deterministic fault injection + the chaos suite.

The four injection seams (ARCHITECTURE §3j):

1. wire frames     → :class:`FrameChaos`    (sources/ingest_server.py)
2. batch delivery  → :class:`BatchChaos`    (source → ingestion surface)
3. shard workers   → :class:`WorkerChaos`   (aggregator/sharded.py)
4. backend sends   → :class:`FlakyTransport`(datastore/backend.py)

`run_chaos_suite` wires them around the real pipeline and checks the
invariant gates (bounded flush/drain, exact row conservation through the
:class:`DropLedger`, monotonic window emission, crash→restart). Entry
points: ``make chaos`` / ``python -m alaz_tpu.chaos`` and
``bench.py --ingest [--chaos SEED]``.
"""

from alaz_tpu.aggregator.sharded import WorkerCrash
from alaz_tpu.chaos.harness import ChaosReport, emitted_rows, run_chaos_suite
from alaz_tpu.chaos.injectors import (
    BatchChaos,
    FlakyTransport,
    FrameChaos,
    WorkerChaos,
)
from alaz_tpu.utils.ledger import DropLedger

__all__ = [
    "BatchChaos",
    "ChaosReport",
    "DropLedger",
    "FlakyTransport",
    "FrameChaos",
    "WorkerChaos",
    "WorkerCrash",
    "emitted_rows",
    "run_chaos_suite",
]
