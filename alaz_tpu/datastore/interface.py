"""The DataStore plugin seam — datastore/datastore.go:3-21 analog.

The reference's interface has one Persist method per resource kind plus
PersistRequest / PersistKafkaEvent / PersistAliveConnection. Here the event
side is columnar (batches of structured rows) and the resource side is a
single generic ``persist_resource`` plus named convenience wrappers, so a
sink implements 4 methods instead of 11. The TPU GNN scorer and the
batching export backend both implement exactly this.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

import numpy as np

from alaz_tpu.events.k8s import EventType, ResourceType


@runtime_checkable
class DataStore(Protocol):
    def persist_requests(self, batch: np.ndarray) -> None:
        """REQUEST_DTYPE rows (PersistRequest analog, batched)."""
        ...

    def persist_kafka_events(self, batch: np.ndarray) -> None:
        """KAFKA_EVENT_DTYPE rows (PersistKafkaEvent analog)."""
        ...

    def persist_alive_connections(self, batch: np.ndarray) -> None:
        """ALIVE_CONNECTION_DTYPE rows (PersistAliveConnection analog)."""
        ...

    def persist_resource(self, rtype: ResourceType, event: EventType, obj: Any) -> None:
        """K8s resource DTO (PersistPod/Service/... analog)."""
        ...


class BaseDataStore:
    """No-op base with the named per-resource wrappers the reference's
    interface spells out (PersistPod, PersistService, ...)."""

    def persist_requests(self, batch: np.ndarray) -> None:  # pragma: no cover
        pass

    def persist_kafka_events(self, batch: np.ndarray) -> None:  # pragma: no cover
        pass

    def persist_alive_connections(self, batch: np.ndarray) -> None:  # pragma: no cover
        pass

    def persist_resource(self, rtype: ResourceType, event: EventType, obj: Any) -> None:
        pass

    # named wrappers (datastore.go:4-14 surface)
    def persist_pod(self, pod, event: EventType) -> None:
        self.persist_resource(ResourceType.POD, event, pod)

    def persist_service(self, svc, event: EventType) -> None:
        self.persist_resource(ResourceType.SERVICE, event, svc)

    def persist_replicaset(self, rs, event: EventType) -> None:
        self.persist_resource(ResourceType.REPLICASET, event, rs)

    def persist_deployment(self, dep, event: EventType) -> None:
        self.persist_resource(ResourceType.DEPLOYMENT, event, dep)

    def persist_endpoints(self, ep, event: EventType) -> None:
        self.persist_resource(ResourceType.ENDPOINTS, event, ep)

    def persist_container(self, c, event: EventType) -> None:
        self.persist_resource(ResourceType.CONTAINER, event, c)

    def persist_daemonset(self, d, event: EventType) -> None:
        self.persist_resource(ResourceType.DAEMONSET, event, d)

    def persist_statefulset(self, s, event: EventType) -> None:
        self.persist_resource(ResourceType.STATEFULSET, event, s)
