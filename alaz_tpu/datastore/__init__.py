"""Pluggable sinks for resolved edges — the datastore/ package analog."""

from alaz_tpu.datastore.dto import (
    REQUEST_DTYPE,
    KAFKA_EVENT_DTYPE,
    ALIVE_CONNECTION_DTYPE,
    EP_NONE,
    EP_POD,
    EP_SERVICE,
    EP_OUTBOUND,
    RequestView,
    iter_request_views,
    make_requests,
    reverse_direction,
)
from alaz_tpu.datastore.interface import DataStore, BaseDataStore
from alaz_tpu.datastore.inmem import InMemDataStore
from alaz_tpu.datastore.backend import BatchingBackend, Transport

__all__ = [
    "REQUEST_DTYPE",
    "KAFKA_EVENT_DTYPE",
    "ALIVE_CONNECTION_DTYPE",
    "EP_NONE",
    "EP_POD",
    "EP_SERVICE",
    "EP_OUTBOUND",
    "RequestView",
    "iter_request_views",
    "make_requests",
    "reverse_direction",
    "DataStore",
    "BaseDataStore",
    "InMemDataStore",
    "BatchingBackend",
    "Transport",
]
