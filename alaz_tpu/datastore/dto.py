"""Columnar DTOs — the datastore/dto.go analog.

The reference's ``Request`` (dto.go:177-198), ``KafkaEvent`` (122-142) and
``AliveConnection`` (96-106) become structured-array rows; strings (UIDs,
methods, paths, topics) are interned int32 ids resolved against the
pipeline's shared :class:`~alaz_tpu.events.intern.Interner` at export time.

``EdgeBatch`` wraps a REQUEST_DTYPE array — it is both the unit the
datastore sinks consume and the raw material of graph batching.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

from alaz_tpu.events.intern import Interner
from alaz_tpu.events.net import u32_to_ip
from alaz_tpu.events.schema import L7Protocol, method_to_string

# From/To endpoint types (dto.go FromType/ToType ∈ pod|service|outbound)
EP_NONE = 0
EP_POD = 1
EP_SERVICE = 2
EP_OUTBOUND = 3

_EP_NAMES = ["", "pod", "service", "outbound"]

REQUEST_DTYPE = np.dtype(
    [
        ("start_time_ms", np.int64),
        ("latency_ns", np.uint64),
        ("from_ip", np.uint32),
        ("from_type", np.uint8),  # EP_*
        ("from_uid", np.int32),  # interned
        ("from_port", np.uint16),
        ("to_ip", np.uint32),
        ("to_type", np.uint8),
        ("to_uid", np.int32),
        ("to_port", np.uint16),
        ("protocol", np.uint8),  # L7Protocol
        ("tls", np.bool_),
        ("completed", np.bool_),
        ("status_code", np.uint32),
        ("fail_reason", np.int32),  # interned
        ("method", np.uint8),  # per-protocol method enum
        ("path", np.int32),  # interned
    ]
)

KAFKA_EVENT_DTYPE = np.dtype(
    [
        ("start_time_ms", np.int64),
        ("latency_ns", np.uint64),
        ("from_ip", np.uint32),
        ("from_type", np.uint8),
        ("from_uid", np.int32),
        ("from_port", np.uint16),
        ("to_ip", np.uint32),
        ("to_type", np.uint8),
        ("to_uid", np.int32),
        ("to_port", np.uint16),
        ("topic", np.int32),  # interned
        ("partition", np.uint32),
        ("key", np.int32),  # interned
        ("value", np.int32),  # interned
        ("type", np.uint8),  # 1=PUBLISH 2=CONSUME
        ("tls", np.bool_),
    ]
)

KAFKA_PUBLISH = 1
KAFKA_CONSUME = 2

ALIVE_CONNECTION_DTYPE = np.dtype(
    [
        ("check_time_ms", np.int64),
        ("from_ip", np.uint32),
        ("from_type", np.uint8),
        ("from_uid", np.int32),
        ("from_port", np.uint16),
        ("to_ip", np.uint32),
        ("to_type", np.uint8),
        ("to_uid", np.int32),
        ("to_port", np.uint16),
    ]
)


def make_requests(n: int) -> np.ndarray:
    return np.zeros(n, dtype=REQUEST_DTYPE)


def reverse_direction(rows: np.ndarray, mask: np.ndarray | None = None) -> None:
    """In-place from/to swap for consume-side events (dto.go:226-231,
    ReverseDirection; applied for AMQP DELIVER / Redis PUSHED_EVENT,
    data.go:1110-1112,1151-1153)."""
    idx = slice(None) if mask is None else mask
    for a, b in (
        ("from_ip", "to_ip"),
        ("from_port", "to_port"),
        ("from_uid", "to_uid"),
        ("from_type", "to_type"),
    ):
        tmp = rows[a][idx].copy()
        rows[a][idx] = rows[b][idx]
        rows[b][idx] = tmp


@dataclass
class RequestView:
    """Scalar, string-resolved view of one REQUEST_DTYPE row — the shape the
    reference's ``datastore.Request`` has. For tests/exports, not hot paths."""

    start_time_ms: int
    latency_ns: int
    from_ip: str
    from_type: str
    from_uid: str
    from_port: int
    to_ip: str
    to_type: str
    to_uid: str
    to_port: int
    protocol: str
    tls: bool
    completed: bool
    status_code: int
    fail_reason: str
    method: str
    path: str


def iter_request_views(rows: np.ndarray, interner: Interner) -> Iterator[RequestView]:
    for r in rows:
        yield RequestView(
            start_time_ms=int(r["start_time_ms"]),
            latency_ns=int(r["latency_ns"]),
            from_ip=u32_to_ip(r["from_ip"]) if r["from_ip"] else "",
            from_type=_EP_NAMES[r["from_type"]],
            from_uid=interner.lookup(int(r["from_uid"])),
            from_port=int(r["from_port"]),
            to_ip=u32_to_ip(r["to_ip"]) if r["to_ip"] else "",
            to_type=_EP_NAMES[r["to_type"]],
            to_uid=interner.lookup(int(r["to_uid"])),
            to_port=int(r["to_port"]),
            # TLS'd HTTP renders as HTTPS at the export boundary
            # (processHttpEvent, data.go:1240-1242)
            protocol=(
                "HTTPS"
                if r["tls"] and r["protocol"] == L7Protocol.HTTP
                else L7Protocol(r["protocol"]).wire_name()
            ),
            tls=bool(r["tls"]),
            completed=bool(r["completed"]),
            status_code=int(r["status_code"]),
            fail_reason=interner.lookup(int(r["fail_reason"])),
            method=method_to_string(int(r["protocol"]), int(r["method"])),
            path=interner.lookup(int(r["path"])),
        )


def request_rows_to_payload(rows: np.ndarray, interner: Interner) -> list[list]:
    """Fixed-arity array payload rows, the ReqInfo[16] wire shape
    (datastore/payload.go:109-130)."""
    out = []
    for v in iter_request_views(rows, interner):
        out.append(
            [
                v.start_time_ms,
                v.latency_ns,
                v.from_ip,
                v.from_type,
                v.from_uid,
                v.from_port,
                v.to_ip,
                v.to_type,
                v.to_uid,
                v.to_port,
                v.protocol,
                v.status_code,
                v.fail_reason,
                v.method,
                v.path,
                v.tls,
            ]
        )
    return out
