"""In-memory datastore — the MockDataStore analog
(main_benchmark_test.go:639-678): counts everything, optionally retains
batches for assertions, and is the CPU-reference sink for replay configs.
"""

from __future__ import annotations

import threading
from typing import Any, List

import numpy as np

from alaz_tpu.datastore.interface import BaseDataStore
from alaz_tpu.events.k8s import EventType, ResourceType


class InMemDataStore(BaseDataStore):
    def __init__(self, retain: bool = False):
        self.retain = retain
        self.request_count = 0
        self.kafka_count = 0
        self.alive_count = 0
        self.resource_counts: dict[str, int] = {}
        self.request_batches: List[np.ndarray] = []
        self.kafka_batches: List[np.ndarray] = []
        self.alive_batches: List[np.ndarray] = []
        self.resources: List[tuple[ResourceType, EventType, Any]] = []
        self._lock = threading.Lock()

    def persist_requests(self, batch: np.ndarray) -> None:
        with self._lock:
            self.request_count += batch.shape[0]
            if self.retain:
                self.request_batches.append(batch.copy())

    def persist_kafka_events(self, batch: np.ndarray) -> None:
        with self._lock:
            self.kafka_count += batch.shape[0]
            if self.retain:
                self.kafka_batches.append(batch.copy())

    def persist_alive_connections(self, batch: np.ndarray) -> None:
        with self._lock:
            self.alive_count += batch.shape[0]
            if self.retain:
                self.alive_batches.append(batch.copy())

    def persist_resource(self, rtype: ResourceType, event: EventType, obj: Any) -> None:
        with self._lock:
            key = rtype.value
            self.resource_counts[key] = self.resource_counts.get(key, 0) + 1
            if self.retain:
                self.resources.append((rtype, event, obj))

    def all_requests(self) -> np.ndarray:
        with self._lock:
            if not self.request_batches:
                from alaz_tpu.datastore.dto import REQUEST_DTYPE

                return np.zeros(0, dtype=REQUEST_DTYPE)
            return np.concatenate(self.request_batches)
