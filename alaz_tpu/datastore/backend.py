"""Batching export backend — the datastore/backend.go analog (G17).

Buffers columnar batches per stream and flushes on batch-size or cadence
(reqs ≤1000/5s, conns ≤500/30s, kafka ≤500/5s, resources ≤1000/5s;
backend.go:280-338,591-765) through a pluggable ``Transport`` with retries
and exponential backoff (2 retries, 1-5s, retry on 400/429/5xx;
backend.go:210-278). Every flush carries ``Metadata`` with a fresh
idempotency key (payload.go:3-8).

The Transport is the process boundary: an HTTP client in production, an
in-process recorder in tests, or the TPU scoring service's feed queue.
"""

from __future__ import annotations

import json
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import numpy as np

from alaz_tpu import __version__
from alaz_tpu.config import BackendConfig
from alaz_tpu.datastore.dto import _EP_NAMES, request_rows_to_payload
from alaz_tpu.events.net import u32_to_ip
from alaz_tpu.datastore.interface import BaseDataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.k8s import EventType, ResourceType
from alaz_tpu.logging import get_logger

log = get_logger("alaz_tpu.datastore")

# endpoint paths mirror backend.go:171-187 (+ the new anomaly-score leg)
EP_REQUESTS = "/requests/"
EP_CONNECTIONS = "/connections/"
EP_KAFKA = "/events/kafka/"
EP_HEALTHCHECK = "/healthcheck/"
EP_ANOMALIES = "/anomalies/"
EP_METRICS = "/metrics/scrape/"  # backend.go:504
_RESOURCE_EP = {
    ResourceType.POD: "/pod/",
    ResourceType.SERVICE: "/svc/",
    ResourceType.REPLICASET: "/rs/",
    ResourceType.DEPLOYMENT: "/deployment/",
    ResourceType.ENDPOINTS: "/endpoint/",
    ResourceType.CONTAINER: "/container/",
    ResourceType.DAEMONSET: "/daemonset/",
    ResourceType.STATEFULSET: "/statefulset/",
}

Transport = Callable[[str, dict], int]
"""(endpoint, json-able payload) -> HTTP-like status code."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker for the export leg (ISSUE 6).

    closed --(threshold consecutive send failures)--> open
    open   --(cooldown elapses)--> half-open: ONE probe send passes
    half-open --probe success--> closed / --probe failure--> open again

    While open, sends short-circuit without touching the wire — a dead
    or drowning backend costs one counter bump per batch instead of a
    full retry ladder (max_retries × backoff) per batch, which is what
    turns a backend brownout into an agent-side CPU/latency incident.
    Thread-safe; ``time_fn`` injectable for tests."""

    def __init__(
        self,
        threshold: int = 5,
        cooldown_s: float = 30.0,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.time_fn = time_fn
        self._lock = threading.Lock()
        self._failures = 0  # consecutive  # guarded-by: self._lock
        self._opened_at: Optional[float] = None  # guarded-by: self._lock
        self._probe_out = False  # a half-open probe is in flight  # guarded-by: self._lock
        self.opens = 0  # guarded-by: self._lock
        self.shorted = 0  # sends skipped while open  # guarded-by: self._lock
        # optional flight recorder (ISSUE 9): open/close flips become
        # structured ring events, so a post-incident dump shows WHEN the
        # export leg went dark relative to the windows it was shedding
        self.recorder = None  # lockless-ok: attach-once wiring before traffic flows; readers null-check an atomic reference swap

    def allow(self) -> bool:
        """May a send go to the wire right now?"""
        with self._lock:
            if self._opened_at is None:
                return True
            if self.time_fn() - self._opened_at >= self.cooldown_s:
                if not self._probe_out:
                    self._probe_out = True  # exactly one probe through
                    return True
            self.shorted += 1
            return False

    def record(self, ok: bool) -> None:
        flip: Optional[str] = None
        with self._lock:
            probe = self._probe_out
            self._probe_out = False
            if ok:
                self._failures = 0
                if self._opened_at is not None:
                    flip = "closed"
                self._opened_at = None
            elif self._opened_at is not None:
                if probe:
                    # failed half-open probe: restart the cooldown window
                    self._opened_at = self.time_fn()
                    self.opens += 1
                    flip = "reopened"
                # else: a STRAGGLER failure — a send that departed before
                # the circuit opened (concurrent pump threads). The
                # outage is already accounted; re-counting it would
                # inflate `opens` and push recovery out a full cooldown.
            else:
                self._failures += 1
                if self._failures >= self.threshold:
                    self._opened_at = self.time_fn()
                    self.opens += 1
                    flip = "opened"
            opens = self.opens
        rec = self.recorder
        if flip is not None and rec is not None:
            # outside the breaker lock: the recorder has its own ring
            # lock and never calls back in
            rec.record("breaker_flip", state=flip, opens=opens)

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self.time_fn() - self._opened_at >= self.cooldown_s:
                return "half-open"
            return "open"


def http_transport(host: str, timeout_s: float = 10.0) -> Transport:
    """Real HTTP POST transport over urllib (the retryablehttp client's
    wire role, backend.go:210-278; retries/backoff live in
    BatchingBackend)."""
    import urllib.error
    import urllib.request

    base = host.rstrip("/")

    def send(endpoint: str, payload: dict) -> int:
        req = urllib.request.Request(
            base + endpoint,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST" if endpoint != EP_HEALTHCHECK else "PUT",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return resp.status
        except urllib.error.HTTPError as e:
            return e.code

    return send


@dataclass
class _Stream:
    """Per-endpoint buffer + delivery accounting. Every field is
    guarded by the owning ``BatchingBackend._lock`` — the backend is
    the only holder of ``_Stream`` references, and alazrace's golden
    concurrency map pins that ownership (the pump thread and the
    caller's flush/stop both account through the one lock; the
    off-lock ``sent += len(chunk)`` this replaced was an ALZ051 lost
    update whenever ``stop(flush=True)`` overlapped a pump tick)."""

    name: str
    endpoint: str
    batch_size: int
    interval_s: float
    pending: List[Any] = field(default_factory=list)
    last_flush: float = 0.0
    sent: int = 0
    failed: int = 0  # exhausted the retry ladder (or non-retryable 4xx)
    shed: int = 0  # short-circuited by the open breaker, never wired


class BatchingBackend(BaseDataStore):
    """Thread-safe; ``pump()`` drives cadence (call from a runtime loop or
    use ``start()`` for a daemon thread)."""

    def __init__(
        self,
        transport: Transport,
        interner: Interner,
        config: Optional[BackendConfig] = None,
        time_fn: Callable[[], float] = time.monotonic,
        sleep_fn: Callable[[float], None] = time.sleep,
        ledger=None,
    ):
        cfg = config if config is not None else BackendConfig()
        self.cfg = cfg
        self.transport = transport
        self.interner = interner
        self.time_fn = time_fn
        self.sleep_fn = sleep_fn
        now = time_fn()
        self._streams = {
            "requests": _Stream("requests", EP_REQUESTS, cfg.batch_size, cfg.req_flush_interval_s, last_flush=now),
            "connections": _Stream("connections", EP_CONNECTIONS, cfg.conn_batch_size, cfg.conn_flush_interval_s, last_flush=now),
            "kafka": _Stream("kafka", EP_KAFKA, cfg.kafka_batch_size, cfg.kafka_flush_interval_s, last_flush=now),
            "anomalies": _Stream("anomalies", EP_ANOMALIES, cfg.batch_size, cfg.req_flush_interval_s, last_flush=now),
        }
        self._resource_streams: dict[ResourceType, _Stream] = {
            rt: _Stream(rt.value, ep, cfg.batch_size, cfg.resource_flush_interval_s, last_flush=now)
            for rt, ep in _RESOURCE_EP.items()
        }
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._warned_endpoints: set = set()  # guarded-by: self._lock
        # flapping-backend protection (ISSUE 6): consecutive failed sends
        # open the circuit; sends shed fast until a cooldown probe heals
        self.breaker = CircuitBreaker(
            threshold=cfg.breaker_threshold,
            cooldown_s=cfg.breaker_cooldown_s,
            time_fn=time_fn,
        )
        # metrics scrape-and-push leg (backend.go:340-392): a render
        # function (Prometheus text) polled every metrics_export_interval_s
        self._metrics_render: Optional[Callable[[], str]] = None  # lockless-ok: attach-once reference swap at wiring; the pump thread may already be live (cmd_serve starts the backend before Service attaches), but readers null-check and an unattached tick merely skips the scrape — nothing is lost or torn
        self._metrics_last_push = now  # guarded-by: self._lock
        self.metrics_pushed = 0  # guarded-by: self._lock
        # drop-ledger hookup (ISSUE 12 satellite): rows the OPEN breaker
        # sheds attribute to the closed `shed` cause, so the export leg
        # joins the conservation equation instead of hiding loss in
        # `stream.failed`; attach-once at wiring (Service adopts the
        # backend into its ledger, the chaos harness passes its own)
        self.ledger = ledger  # lockless-ok: attach-once reference swap at wiring; the pump thread may already be live (cmd_serve starts the backend before Service adopts it), but no rows are appended until wiring completes, so no shed can precede the swap — readers null-check

    # -- DataStore surface -------------------------------------------------

    def persist_requests(self, batch: np.ndarray) -> None:
        rows = request_rows_to_payload(batch, self.interner)
        self._append("requests", rows)

    def persist_kafka_events(self, batch: np.ndarray) -> None:
        """KafkaEventInfo[16] arity (payload.go:163-180): StartTime, Latency,
        SrcIP, SrcType, SrcID, SrcPort, DstIP, DstType, DstID, DstPort,
        Topic, Partition, Key, Value, Type, Tls."""
        lookup = self.interner.lookup
        rows = [
            [
                int(r["start_time_ms"]), int(r["latency_ns"]),
                u32_to_ip(int(r["from_ip"])) if r["from_ip"] else "",
                _EP_NAMES[int(r["from_type"])], lookup(int(r["from_uid"])),
                int(r["from_port"]),
                u32_to_ip(int(r["to_ip"])) if r["to_ip"] else "",
                _EP_NAMES[int(r["to_type"])], lookup(int(r["to_uid"])),
                int(r["to_port"]),
                lookup(int(r["topic"])), int(r["partition"]),
                lookup(int(r["key"])), lookup(int(r["value"])),
                "PUBLISH" if int(r["type"]) == 1 else "CONSUME", bool(r["tls"]),
            ]
            for r in batch
        ]
        self._append("kafka", rows)

    def persist_alive_connections(self, batch: np.ndarray) -> None:
        """ConnInfo[9] arity (payload.go:137-150): CheckTime, SrcIP, SrcType,
        SrcID, SrcPort, DstIP, DstType, DstID, DstPort."""
        lookup = self.interner.lookup
        rows = [
            [
                int(r["check_time_ms"]),
                u32_to_ip(int(r["from_ip"])) if r["from_ip"] else "",
                _EP_NAMES[int(r["from_type"])], lookup(int(r["from_uid"])),
                int(r["from_port"]),
                u32_to_ip(int(r["to_ip"])) if r["to_ip"] else "",
                _EP_NAMES[int(r["to_type"])], lookup(int(r["to_uid"])),
                int(r["to_port"]),
            ]
            for r in batch
        ]
        self._append("connections", rows)

    def persist_scores(self, records) -> None:
        """Anomaly-score edge annotations → /anomalies/ (the BASELINE.json
        return leg: scores flow back through the dto path) with the
        fixed-arity row discipline of backend.go:819-877. Accepts a
        runtime.ScoreBatch (whose iteration resolves uid strings once per
        unique node using the batch's own interner) or any iterable of
        ScoreRecord-shaped objects."""
        rows = [
            [r.window_start_ms, r.from_uid, r.to_uid, r.protocol, r.score]
            for r in records
        ]
        self._append("anomalies", rows)

    def persist_resource(self, rtype: ResourceType, event: EventType, obj: Any) -> None:
        stream = self._resource_streams[rtype]
        body = dict(obj.__dict__) if hasattr(obj, "__dict__") else obj
        with self._lock:
            stream.pending.append({"event": event.value, "body": _jsonable(body)})

    # -- batching ----------------------------------------------------------

    def _append(self, name: str, rows: List[Any]) -> None:
        stream = self._streams[name]
        with self._lock:
            stream.pending.extend(rows)

    def attach_metrics(self, render_fn: Callable[[], str]) -> None:
        """Register the metrics source for the scrape-and-push leg — the
        reference scrapes its embedded exporters and POSTs the Prometheus
        text to /metrics/scrape/ on a ticker (backend.go:355-392,503-530)."""
        self._metrics_render = render_fn

    def _push_metrics(self) -> None:
        endpoint = (
            f"{EP_METRICS}?instance={self.cfg.node_id}"
            f"&monitoring_id={self.cfg.monitoring_id}"
        )
        try:
            text = self._metrics_render()
            status = self.transport(endpoint, {"text": text})
        except Exception as exc:
            log.warning(f"metrics push failed: {exc}")
            return
        if status < 400:
            with self._lock:
                self.metrics_pushed += 1
        else:
            log.warning(f"metrics push not success: {status}")

    def pump(self, force: bool = False) -> None:
        """Flush every stream that hit its batch size or cadence; push the
        metrics scrape when its interval elapses. Concurrency-safe
        against itself: the pump thread and a caller's ``stop(flush=True)``
        / manual pump both run this, so ALL accounting happens under
        ``self._lock`` (alazrace ALZ050/051: the cadence stamp and the
        sent/failed tallies used to race exactly that overlap)."""
        now = self.time_fn()
        push_due = False
        if self._metrics_render is not None and self.cfg.metrics_export:
            with self._lock:
                push_due = (
                    force
                    or now - self._metrics_last_push
                    >= self.cfg.metrics_export_interval_s
                )
                if push_due:
                    # stamp INSIDE the lock: two racing pumps must not
                    # both see "due" and double-push the scrape
                    self._metrics_last_push = now
        if push_due:
            self._push_metrics()
        for stream in list(self._streams.values()) + list(self._resource_streams.values()):
            with self._lock:
                due = (
                    force
                    or len(stream.pending) >= stream.batch_size
                    or (stream.pending and now - stream.last_flush >= stream.interval_s)
                )
                if not due or not stream.pending:
                    if due:
                        stream.last_flush = now
                    continue
                todo = stream.pending
                stream.pending = []
                stream.last_flush = now
            # send outside the lock, chunked to batch_size
            for i in range(0, len(todo), stream.batch_size):
                chunk = todo[i : i + stream.batch_size]
                outcome = self._send(stream.endpoint, chunk)
                with self._lock:
                    if outcome == "sent":
                        stream.sent += len(chunk)
                    elif outcome == "shed":
                        stream.shed += len(chunk)
                    else:
                        stream.failed += len(chunk)
                if outcome == "shed" and self.ledger is not None:
                    # outside the backend lock: the ledger has its own
                    self.ledger.add(
                        "shed", len(chunk), reason="breaker_open"
                    )

    def _send(self, endpoint: str, rows: List[Any]) -> str:
        """One chunk's delivery fate: ``"sent"`` | ``"failed"`` (retry
        ladder exhausted, or non-retryable 4xx) | ``"shed"`` (open
        breaker short-circuit — attributed to the drop ledger by the
        caller)."""
        if not self.breaker.allow():
            # circuit open: shed without touching the wire — one counter
            # bump + a ledger attribution instead of a retry ladder
            return "shed"
        payload = {
            "metadata": {
                "monitoring_id": self.cfg.monitoring_id,
                "idempotency_key": str(uuid.uuid4()),
                "node_id": self.cfg.node_id,
                "alaz_version": __version__,
            },
            "data": rows,
        }
        backoff = self.cfg.backoff_min_s
        for attempt in range(self.cfg.max_retries + 1):
            try:
                status = self.transport(endpoint, payload)
            except Exception as exc:  # transport failure == retryable
                log.warning(f"transport error on {endpoint}: {exc}")
                status = 599
            if status < 400:
                self.breaker.record(True)
                return "sent"
            if status not in (400, 429) and status < 500:
                # non-retryable 4xx: drop loudly (once per endpoint) so a
                # backend without this endpoint doesn't silently eat data.
                # The backend ANSWERED — availability-wise that's a
                # success, so the breaker doesn't count it.
                with self._lock:  # warn-once latch is check-then-act
                    first_drop = endpoint not in self._warned_endpoints
                    if first_drop:
                        self._warned_endpoints.add(endpoint)
                if first_drop:
                    log.warning(
                        f"dropping batch for {endpoint}: non-retryable HTTP {status}"
                    )
                self.breaker.record(True)
                return "failed"
            if attempt < self.cfg.max_retries:
                # exponential backoff with FULL jitter (not a fixed 0.1s
                # additive fuzz): N agents retrying a recovered backend
                # spread over the whole window instead of stampeding at
                # backoff-aligned instants
                self.sleep_fn(
                    random.uniform(0, min(backoff, self.cfg.backoff_max_s))
                )
                backoff *= 2
        self.breaker.record(False)
        return "failed"

    # -- lifecycle ---------------------------------------------------------

    def start(self, poll_interval_s: float = 0.5) -> None:
        """Daemon flusher thread (sendReqsInBatch-style tickers)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def run() -> None:
            while not self._stop.is_set():
                self.pump()
                self._stop.wait(poll_interval_s)

        self._thread = threading.Thread(target=run, name="alaz-backend-pump", daemon=True)
        self._thread.start()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if flush:
            self.pump(force=True)

    def stats(self) -> dict:
        out = {}
        with self._lock:
            for s in list(self._streams.values()) + list(self._resource_streams.values()):
                out[s.name] = {
                    "pending": len(s.pending),
                    "sent": s.sent,
                    "failed": s.failed,
                    "shed": s.shed,
                }
        out["breaker"] = {
            "state": self.breaker.state,
            "opens": self.breaker.opens,
            "shorted": self.breaker.shorted,
        }
        return out


def _jsonable(obj: Any) -> Any:
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return json.loads(json.dumps(obj, default=lambda o: getattr(o, "__dict__", str(o))))
