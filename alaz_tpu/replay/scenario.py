"""Anomaly-detection scenarios: replay + fault injection + labeled windows.

Wires the full path of BASELINE.json configs 2-4: simulator traffic →
aggregator join → fault injector → windowed graph store → labeled
GraphBatches split into train/eval window ranges.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np

from alaz_tpu.aggregator.engine import Aggregator
from alaz_tpu.config import SimulationConfig
from alaz_tpu.datastore.interface import BaseDataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.graph.builder import WindowedGraphStore
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.replay import faults as faults_mod
from alaz_tpu.replay.incidents import replay_delivery
from alaz_tpu.replay.simulator import _BASE_TIME_NS, Simulator


class FaultInjectingStore(BaseDataStore):
    """Datastore shim: injects faults into request rows, then forwards to
    the windowed graph store — the seam where reality goes wrong."""

    def __init__(self, inner: WindowedGraphStore, plan: faults_mod.FaultPlan, rng: np.random.Generator):
        self.inner = inner
        self.plan = plan
        self.rng = rng

    def persist_requests(self, batch: np.ndarray) -> None:
        rows = batch.copy()
        labels = faults_mod.inject(rows, self.plan, self.rng)
        rows, labels = faults_mod.drop_zombie_rows(rows, labels, self.plan, self.rng)
        self.inner.persist_requests(rows)

    def persist_resource(self, rtype, event, obj) -> None:
        self.inner.persist_resource(rtype, event, obj)


@dataclass
class ScenarioData:
    train: List[GraphBatch]
    eval: List[GraphBatch]
    interner: Interner
    plan: faults_mod.FaultPlan
    # request rows cut by degree-capped sampling (ISSUE 7) — lets the
    # sampling-parity gate assert the cap actually BIT, not that it was
    # vacuously within tolerance
    sampled_rows: int = 0

    @property
    def all_batches(self) -> List[GraphBatch]:
        return self.train + self.eval


def _run_scenario(
    sim_cfg: SimulationConfig,
    n_windows: int,
    window_s: float,
    train_frac: float,
    seed: int,
    plan_fn,
    label_fn,
    chaos=None,
    incident=None,
    degree_cap: int = 0,
) -> ScenarioData:
    """The shared scenario pipeline: simulate → inject per ``plan_fn(rng,
    uid_pairs)`` → aggregate into labeled windows via ``label_fn(batch,
    plan)`` → time-split. Both public scenarios are thin wrappers so the
    replay plumbing (flush timing, store wiring) can never diverge.

    ``chaos`` (a :class:`alaz_tpu.chaos.BatchChaos`) perturbs the L7
    delivery — duplicated/reordered/late batches — BEFORE the
    aggregator, replaying infrastructure faults under the semantic fault
    plan: the chaos-AUROC gate trains and evaluates on exactly this
    degraded stream (ISSUE 6 acceptance).

    ``incident`` (an :class:`alaz_tpu.replay.incidents.Incident`, or a
    list of them) reshapes the traffic itself — hot-key fan-in, deploy
    rollout churn, retry storms (ISSUE 7). Incidents compose with chaos:
    the incident shapes the stream, chaos degrades its delivery, so
    "hot-key during a degraded delivery" is one call with both args.
    Incident-labeled pairs (e.g. the retry storm's victim edges) fold
    into the oracle next to the fault plan's labels.

    ``degree_cap`` arms degree-capped reservoir sampling at window close
    (graph/builder.py) — the hot-key defense under detection test."""
    from alaz_tpu.replay import incidents as incidents_mod

    rng = np.random.default_rng(seed)
    interner = Interner()
    sim = Simulator(
        SimulationConfig(
            **{
                **sim_cfg.__dict__,
                "test_duration_s": n_windows * window_s,
            }
        ),
        interner=interner,
    )
    kube_msgs = sim.setup()

    # fault plan over the simulator's edge set (uid-id pairs)
    pairs = [
        (
            interner.intern(sim.pods[e.pod_idx].uid),
            interner.intern(sim.services[e.svc_idx].uid),
        )
        for e in sim.edges
    ]
    plan = plan_fn(rng, pairs)

    store = WindowedGraphStore(
        interner, window_s=window_s, degree_cap=degree_cap, sample_seed=seed
    )
    injected = FaultInjectingStore(store, plan, rng)
    agg = Aggregator(injected, interner=interner)
    for m in kube_msgs:
        agg.process_k8s(m)
    agg.process_tcp(sim.tcp_events())

    traffic = incidents_mod.base_traffic(sim)
    if incident is not None:
        for inc in incident if isinstance(incident, (list, tuple)) else [incident]:
            traffic = inc.apply(sim, traffic)
    deliveries = traffic.deliveries
    if chaos is not None:
        # late batches re-deliver at the end of the stream — past their
        # windows' watermarks, so they exercise the late-drop path
        delivery, late = chaos.perturb(deliveries)
        deliveries = delivery + late
    for d in deliveries:
        replay_delivery(agg, d)
    agg.flush_retries(now_ns=_BASE_TIME_NS + int((n_windows + 10) * window_s * 1e9))
    store.flush()

    batches = store.batches
    for b in batches:
        label_fn(b, plan)
        if traffic.label_pairs:
            extra = incidents_mod.label_extra(
                b, traffic.label_pairs, traffic.label_span_ms
            )
            b.edge_label = np.maximum(b.edge_label, extra)

    n_train = max(1, int(len(batches) * train_frac))
    return ScenarioData(
        train=batches[:n_train],
        eval=batches[n_train:],
        interner=interner,
        plan=plan,
        sampled_rows=store.builder.sampled_rows,
    )


def run_anomaly_scenario(
    sim_cfg: SimulationConfig,
    n_windows: int = 10,
    window_s: float = 1.0,
    fault_fraction: float = 0.15,
    train_frac: float = 0.6,
    fault_kinds: tuple = faults_mod.FAULT_KINDS,
    seed: int = 0,
    chaos=None,
    incident=None,
    degree_cap: int = 0,
) -> ScenarioData:
    """Replay ``n_windows`` of traffic with a persistent fault plan, label
    every closed window with the oracle, and split train/eval by time.
    ``chaos`` (optional BatchChaos) degrades the delivery plane — the
    detection-under-chaos gate runs this with default intensities.
    ``incident`` (optional Incident(s), replay/incidents.py) reshapes
    the traffic itself and ``degree_cap`` arms close-time sampling, so
    "hot-key during a degraded delivery, capped" is one call."""

    def label(b, plan):
        b.edge_label = faults_mod.label_batch_edges(b, plan)
        # per-class oracle for kind-broken-out AUROC (metrics.auroc_by_kind)
        b.edge_fault_kind = faults_mod.label_batch_kinds(b, plan)

    return _run_scenario(
        sim_cfg, n_windows, window_s, train_frac, seed,
        plan_fn=lambda rng, pairs: faults_mod.make_plan(
            rng, pairs, fault_fraction, kinds=fault_kinds
        ),
        label_fn=label,
        chaos=chaos,
        incident=incident,
        degree_cap=degree_cap,
    )


def run_forecast_scenario(
    sim_cfg: SimulationConfig,
    n_windows: int = 20,
    window_s: float = 1.0,
    fault_fraction: float = 0.15,
    train_frac: float = 0.6,
    ramp_windows: int = 4,
    full_mult: float = 12.0,
    seed: int = 0,
) -> ScenarioData:
    """BASELINE config 4's FORECASTING task: latency faults RAMP over
    ``ramp_windows`` windows instead of stepping, and every batch carries
    ``edge_label_next`` — what the edge's spike label WILL be at the end
    of the NEXT window. A temporal model watching the sub-threshold
    drift (the leading indicator) can call the spike one window early;
    train on ``edge_label_next`` and evaluate AUROC against it
    (train/trainstep.py train_tgn_unrolled(label_attr=...)).

    Onsets are spread over the middle of the run so both the train and
    eval spans contain pre-onset, ramping, and spiking states."""
    window_ms = int(window_s * 1000)
    base_ms = _BASE_TIME_NS // 1_000_000

    def label(b, plan):
        b.edge_label = faults_mod.label_batch_edges(b, plan)
        b.edge_fault_kind = faults_mod.label_batch_kinds(b, plan)
        # the forecast target: this edge's spike state at the END of the
        # next window
        b.edge_label_next = faults_mod.label_batch_edges(
            b, plan, at_ms=int(b.window_end_ms) + window_ms
        )

    return _run_scenario(
        sim_cfg, n_windows, window_s, train_frac, seed,
        plan_fn=lambda rng, pairs: faults_mod.make_ramp_plan(
            rng,
            pairs,
            fault_fraction,
            onset_lo_ms=base_ms + window_ms,
            onset_hi_ms=base_ms + (n_windows - ramp_windows // 2) * window_ms,
            span_ms=ramp_windows * window_ms,
            full_mult=full_mult,
        ),
        label_fn=label,
    )
