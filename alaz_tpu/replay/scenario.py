"""Anomaly-detection scenarios: replay + fault injection + labeled windows.

Wires the full path of BASELINE.json configs 2-4: simulator traffic →
aggregator join → fault injector → windowed graph store → labeled
GraphBatches split into train/eval window ranges.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np

from alaz_tpu.aggregator.engine import Aggregator
from alaz_tpu.config import SimulationConfig
from alaz_tpu.datastore.interface import BaseDataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.graph.builder import WindowedGraphStore
from alaz_tpu.graph.snapshot import GraphBatch
from alaz_tpu.replay import faults as faults_mod
from alaz_tpu.replay.simulator import _BASE_TIME_NS, Simulator


class FaultInjectingStore(BaseDataStore):
    """Datastore shim: injects faults into request rows, then forwards to
    the windowed graph store — the seam where reality goes wrong."""

    def __init__(self, inner: WindowedGraphStore, plan: faults_mod.FaultPlan, rng: np.random.Generator):
        self.inner = inner
        self.plan = plan
        self.rng = rng

    def persist_requests(self, batch: np.ndarray) -> None:
        rows = batch.copy()
        labels = faults_mod.inject(rows, self.plan, self.rng)
        rows, labels = faults_mod.drop_zombie_rows(rows, labels, self.plan, self.rng)
        self.inner.persist_requests(rows)

    def persist_resource(self, rtype, event, obj) -> None:
        self.inner.persist_resource(rtype, event, obj)


@dataclass
class ScenarioData:
    train: List[GraphBatch]
    eval: List[GraphBatch]
    interner: Interner
    plan: faults_mod.FaultPlan

    @property
    def all_batches(self) -> List[GraphBatch]:
        return self.train + self.eval


def run_anomaly_scenario(
    sim_cfg: SimulationConfig,
    n_windows: int = 10,
    window_s: float = 1.0,
    fault_fraction: float = 0.15,
    train_frac: float = 0.6,
    fault_kinds: tuple = faults_mod.FAULT_KINDS,
    seed: int = 0,
) -> ScenarioData:
    """Replay ``n_windows`` of traffic with a persistent fault plan, label
    every closed window with the oracle, and split train/eval by time."""
    rng = np.random.default_rng(seed)
    interner = Interner()
    sim = Simulator(
        SimulationConfig(
            **{
                **sim_cfg.__dict__,
                "test_duration_s": n_windows * window_s,
            }
        ),
        interner=interner,
    )
    kube_msgs = sim.setup()

    # fault plan over the simulator's edge set (uid-id pairs)
    pairs = [
        (
            interner.intern(sim.pods[e.pod_idx].uid),
            interner.intern(sim.services[e.svc_idx].uid),
        )
        for e in sim.edges
    ]
    plan = faults_mod.make_plan(rng, pairs, fault_fraction, kinds=fault_kinds)

    store = WindowedGraphStore(interner, window_s=window_s)
    injected = FaultInjectingStore(store, plan, rng)
    agg = Aggregator(injected, interner=interner)
    for m in kube_msgs:
        agg.process_k8s(m)
    agg.process_tcp(sim.tcp_events())
    for batch in sim.iter_l7_batches():
        agg.process_l7(batch, now_ns=int(batch["write_time_ns"][-1]))
    agg.flush_retries(now_ns=_BASE_TIME_NS + int((n_windows + 10) * window_s * 1e9))
    store.flush()

    batches = store.batches
    for b in batches:
        b.edge_label = faults_mod.label_batch_edges(b, plan)
        # per-class oracle for kind-broken-out AUROC (metrics.auroc_by_kind)
        b.edge_fault_kind = faults_mod.label_batch_kinds(b, plan)

    n_train = max(1, int(len(batches) * train_frac))
    return ScenarioData(
        train=batches[:n_train],
        eval=batches[n_train:],
        interner=interner,
        plan=plan,
    )
