"""Fault injection for anomaly-detection evaluation.

The reference README's failure taxonomy (README.md:51-57 — the classes
Alaz's SaaS surfaces) is the label space:

- ``latency_spike`` — an edge's latencies multiply by ~10
- ``error_burst``   — a large fraction of an edge's responses go 5xx
- ``zombie``        — a service stops answering (requests marked failed,
  traffic collapses)

Faults are injected on *request rows* (post-aggregator, pre-window) for a
chosen set of edges over a window span; the oracle then labels aggregated
GraphBatch edges by (src_uid, dst_uid) membership, which is the ground
truth AUROC is computed against (BASELINE.json ≥0.9 gate).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

import numpy as np

LATENCY_SPIKE = "latency_spike"
ERROR_BURST = "error_burst"
ZOMBIE = "zombie"

FAULT_KINDS = (LATENCY_SPIKE, ERROR_BURST, ZOMBIE)

# a ramped latency fault COUNTS as a spike once its multiplier crosses
# this (the labels flip here; below it the drift is a leading indicator
# the forecaster is allowed to see)
SPIKE_THRESHOLD = 4.0


@dataclass
class FaultPlan:
    """Which (from_uid, to_uid) edges are faulty, with what, and when.

    ``ramps`` makes a latency fault develop gradually instead of
    stepping: pair -> (onset_ms, span_ms, full_mult), multiplier ramping
    1 → full_mult linearly over [onset, onset+span]. Rows are labeled
    faulty only once the multiplier crosses SPIKE_THRESHOLD — the
    sub-threshold drift is the leading indicator that makes
    next-window forecasting (BASELINE config 4) a learnable task
    rather than clairvoyance."""

    # (from_uid_id, to_uid_id) -> fault kind
    edges: Dict[Tuple[int, int], str] = field(default_factory=dict)
    start_ms: int = 0
    end_ms: int = 1 << 62
    # (from_uid_id, to_uid_id) -> (onset_ms, span_ms, full_mult)
    ramps: Dict[Tuple[int, int], Tuple[int, int, float]] = field(default_factory=dict)

    def active(self, window_start_ms: int) -> bool:
        return self.start_ms <= window_start_ms < self.end_ms

    @property
    def edge_set(self) -> Set[Tuple[int, int]]:
        return set(self.edges)

    def ramp_multiplier(self, pair: Tuple[int, int], t_ms) -> np.ndarray:
        """Vectorized over t_ms; 1.0 outside the ramp's support."""
        onset, span, full = self.ramps[pair]
        u = np.clip((np.asarray(t_ms, np.float64) - onset) / max(span, 1), 0.0, 1.0)
        return 1.0 + (full - 1.0) * u


def make_ramp_plan(
    rng: np.random.Generator,
    edge_uid_pairs: List[Tuple[int, int]],
    fault_fraction: float = 0.15,
    onset_lo_ms: int = 0,
    onset_hi_ms: int = 1,
    span_ms: int = 4000,
    full_mult: float = 12.0,
) -> FaultPlan:
    """Latency faults that RAMP: each picked edge drifts 1x → full_mult
    over ``span_ms`` starting at a random onset in [onset_lo, onset_hi).
    The forecast scenario trains models to call the spike BEFORE the
    threshold crossing (replay/scenario.py run_forecast_scenario)."""
    n_faulty = max(1, int(len(edge_uid_pairs) * fault_fraction))
    pick = rng.choice(len(edge_uid_pairs), size=n_faulty, replace=False)
    plan = FaultPlan()
    for i in pick:
        pair = edge_uid_pairs[int(i)]
        plan.edges[pair] = LATENCY_SPIKE
        plan.ramps[pair] = (
            int(rng.integers(onset_lo_ms, max(onset_hi_ms, onset_lo_ms + 1))),
            int(span_ms),
            float(full_mult),
        )
    return plan


def make_plan(
    rng: np.random.Generator,
    edge_uid_pairs: List[Tuple[int, int]],
    fault_fraction: float = 0.15,
    kinds: tuple = FAULT_KINDS,
    start_ms: int = 0,
    end_ms: int = 1 << 62,
) -> FaultPlan:
    n_faulty = max(1, int(len(edge_uid_pairs) * fault_fraction))
    pick = rng.choice(len(edge_uid_pairs), size=n_faulty, replace=False)
    plan = FaultPlan(start_ms=start_ms, end_ms=end_ms)
    for i in pick:
        plan.edges[edge_uid_pairs[int(i)]] = kinds[int(rng.integers(0, len(kinds)))]
    return plan


def inject(rows: np.ndarray, plan: FaultPlan, rng: np.random.Generator) -> np.ndarray:
    """Mutate REQUEST_DTYPE rows in place per the plan; returns per-row
    0/1 labels (ground truth at request granularity)."""
    labels = np.zeros(rows.shape[0], dtype=np.float32)
    if not plan.edges:
        return labels
    if rows.shape[0] == 0:
        return labels
    active = plan.active(int(rows["start_time_ms"].min()))
    if not active:
        return labels
    pair = rows["from_uid"].astype(np.int64) << 32 | rows["to_uid"].astype(np.int64)
    for (fu, tu), kind in plan.edges.items():
        mask = pair == (np.int64(fu) << 32 | np.int64(tu))
        if not mask.any():
            continue
        idx = np.flatnonzero(mask)
        if (fu, tu) in plan.ramps:
            # ramped latency: per-row multiplier from the row's own time;
            # rows count as faulty only past the spike threshold
            m = plan.ramp_multiplier((fu, tu), rows["start_time_ms"][idx])
            rows["latency_ns"][idx] = (
                rows["latency_ns"][idx].astype(np.float64)
                * m * rng.uniform(0.9, 1.1, idx.shape[0])
            ).astype(np.uint64)
            labels[idx] = (m >= SPIKE_THRESHOLD).astype(np.float32)
            continue
        labels[mask] = 1.0
        if kind == LATENCY_SPIKE:
            rows["latency_ns"][idx] = (
                rows["latency_ns"][idx].astype(np.float64)
                * rng.uniform(8.0, 15.0, idx.shape[0])
            ).astype(np.uint64)
        elif kind == ERROR_BURST:
            hit = idx[rng.random(idx.shape[0]) < 0.8]
            rows["status_code"][hit] = 500
        elif kind == ZOMBIE:
            # service stops answering: requests fail, most traffic vanishes
            rows["completed"][idx] = False
            rows["status_code"][idx] = 0
    return labels


def drop_zombie_rows(rows: np.ndarray, labels: np.ndarray, plan: FaultPlan, rng: np.random.Generator, keep_frac: float = 0.1):
    """Zombie edges lose most of their traffic; apply after inject()."""
    if not plan.edges:
        return rows, labels
    pair = rows["from_uid"].astype(np.int64) << 32 | rows["to_uid"].astype(np.int64)
    drop = np.zeros(rows.shape[0], dtype=bool)
    for (fu, tu), kind in plan.edges.items():
        if kind != ZOMBIE:
            continue
        mask = pair == (np.int64(fu) << 32 | np.int64(tu))
        drop |= mask & (rng.random(rows.shape[0]) > keep_frac)
    return rows[~drop], labels[~drop]


def _pack_pairs(fu: np.ndarray, tu: np.ndarray) -> np.ndarray:
    return fu.astype(np.int64) << 32 | tu.astype(np.int64)


def label_batch_kinds(batch, plan: FaultPlan, kind_names: tuple = FAULT_KINDS) -> np.ndarray:
    """Per-edge fault KIND labels: 0 = clean, else 1 + index into
    ``kind_names``. Lets evaluation break AUROC out per failure class —
    a model that only catches error bursts must not hide behind a
    blended number. Plan kinds outside ``kind_names`` stay 0 here (the
    binary oracle still labels them faulty); vectorized with one np.isin
    pass per kind like label_batch_edges."""
    kinds = np.zeros(batch.e_pad, dtype=np.int32)
    if batch.node_uids is None or not plan.active(batch.window_start_ms) or not plan.edges:
        return kinds
    uids = batch.node_uids
    edge_keys = _pack_pairs(uids[batch.edge_src], uids[batch.edge_dst])
    spiking = set(_spiking_keys(plan, int(batch.window_end_ms)).tolist())
    for i, name in enumerate(kind_names):
        keys = np.array(
            [
                k
                for (fu, tu), kd in plan.edges.items()
                if kd == name and (k := int(fu) << 32 | int(tu)) in spiking
            ],
            dtype=np.int64,
        )
        if keys.size == 0:
            continue
        hit = np.isin(edge_keys, keys)
        hit[batch.n_edges :] = False
        kinds[hit] = i + 1
    return kinds


def _spiking_keys(plan: FaultPlan, at_ms: int) -> np.ndarray:
    """Packed keys of plan edges that count as FAULTY at ``at_ms``:
    non-ramped edges always (while the plan is active), ramped edges only
    once their multiplier has crossed SPIKE_THRESHOLD."""
    keys = []
    for (fu, tu) in plan.edges:
        if (fu, tu) in plan.ramps:
            if float(plan.ramp_multiplier((fu, tu), at_ms)) < SPIKE_THRESHOLD:
                continue
        keys.append(int(fu) << 32 | int(tu))
    return np.array(keys, dtype=np.int64)


def label_batch_edges(batch, plan: FaultPlan, at_ms: int | None = None) -> np.ndarray:
    """Oracle labels for an aggregated GraphBatch: edge is faulty iff its
    (src_uid, dst_uid) is in the plan and the window overlaps the span —
    for RAMPED edges, iff the multiplier has crossed SPIKE_THRESHOLD by
    ``at_ms`` (default: the window's END, the end-of-window state).
    Passing a future ``at_ms`` (e.g. next window's end) yields the
    forecast target: what this edge's label WILL be. Vectorized via the
    same packed int64 pair key inject() matches on."""
    labels = np.zeros(batch.e_pad, dtype=np.float32)
    if batch.node_uids is None or not plan.active(batch.window_start_ms) or not plan.edges:
        return labels
    t = int(at_ms) if at_ms is not None else int(batch.window_end_ms)
    plan_keys = _spiking_keys(plan, t)
    if plan_keys.size == 0:
        return labels
    uids = batch.node_uids
    edge_keys = _pack_pairs(uids[batch.edge_src], uids[batch.edge_dst])
    hit = np.isin(edge_keys, plan_keys)
    hit[batch.n_edges :] = False
    labels[hit] = 1.0
    return labels
