"""Multi-tenant replay + the isolation gate (ISSUE 14 proof leg).

K simulators — one per tenant, each with its own rate and protocol
mix — drive ONE :class:`~alaz_tpu.runtime.service.Service` through its
tenancy plane (per-tenant partitions, shared scorer with cross-tenant
batching), optionally with one tenant running an incident
(replay/incidents.py) and/or chaos worker kills on its pool. The gate is
the ISSUE 14 isolation contract:

1. **Per-tenant conservation, exact.** For every tenant,
   ``pushed == scored-window rows + ledger.total`` — one tenant's
   losses can never hide in (or leak into) another's books.
2. **Clean tenants hold latency.** Each clean tenant's p99
   close→score latency in the combined run stays within 10% of its
   SOLO baseline (same traffic, alone on a single-tenant service) —
   with a small absolute floor (``LATENCY_FLOOR_S``): below macroscopic
   latencies, shared-CI scheduler jitter swamps a pure ratio, while a
   real head-of-line regression (tenant A's backlog stalling tenant B's
   windows) shows up in whole window-lengths and trips both terms.
3. **Clean tenants' drift detectors stay silent.** The perturbed
   tenant's score distribution may move (that is its incident doing its
   job — recorded, not gated); a clean tenant's per-tenant drift plane
   paging because of a NEIGHBOR's incident is the cross-contamination
   tenancy exists to prevent.
4. **Exactly-once ascending windows per tenant.**

Scoring runs the **deterministic host scorer** (the feature-space
logistic of obs/scores.py, in logit form) through the Service's real
scorer loop — queues → partitions → window queue → group batching →
``record_window`` — so the gate measures the serving plane, not XLA
compile jitter; the scores themselves are bit-reproducible.

``python -m alaz_tpu.replay --isolation`` (in ``make scenarios``) runs
the K=3 fixed-seed gate; ``python -m alaz_tpu.chaos --tenants`` (in
``make chaos``) runs the two-tenant worker-kill composition proving
per-tenant conservation under crashes. ``bench.py --ingest --tenants K``
reuses :func:`tenant_serving_bench` for the unpaced throughput record.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from alaz_tpu.config import ChaosConfig, RuntimeConfig, SimulationConfig, TraceConfig
from alaz_tpu.logging import get_logger
from alaz_tpu.replay.incidents import Traffic, base_traffic, make_incident
from alaz_tpu.replay.simulator import Simulator

log = get_logger("alaz_tpu.tenants")

# isolation-gate latency terms (module docstring): ratio per the ISSUE
# acceptance bar, floor to keep sub-scheduler-quantum noise from
# flapping a gate that exists to catch whole-window head-of-line stalls
LATENCY_RATIO = 1.10
LATENCY_FLOOR_S = 0.5

# per-tenant traffic personalities: rate multipliers and protocol mixes
# cycle over these, so K fleets never look alike on the wire
_TENANT_MIXES = (
    {"HTTP": 1.0},
    {"HTTP": 0.5, "POSTGRES": 0.3, "REDIS": 0.2},
    {"HTTP": 0.4, "REDIS": 0.3, "MYSQL": 0.3},
    {"POSTGRES": 0.6, "MYSQL": 0.4},
)
_TENANT_RATES = (150, 250, 200, 350)


# ---------------------------------------------------------------------------
# Deterministic host scorer: obs.scores' feature read in logit form
# (ONE weight definition — record_window applies the sigmoid, so the
# per-tenant planes see EXACTLY feature_scores' distribution), over the
# scorer's graph dicts (serial) and stacked arenas (grouped). Both
# return FRESH arrays (the arithmetic copies), honoring the
# score_many_fn ownership contract (Service docstring): the stacked
# input is a reused double-buffered arena.
# ---------------------------------------------------------------------------


def host_score_fn(params, graph) -> dict:
    from alaz_tpu.obs.scores import feature_logits

    return {"edge_logits": feature_logits(graph["edge_feats"])}


def host_score_many_fn(params, stacked) -> dict:
    from alaz_tpu.obs.scores import feature_logits

    return {"edge_logits": feature_logits(stacked["edge_feats"])}


# ---------------------------------------------------------------------------
# Per-tenant traffic + delivery through the Service submit surface.
# ---------------------------------------------------------------------------


def make_tenant_traffic(
    tenant: int,
    seed: int,
    n_windows: int,
    incident: Optional[str] = None,
    scale: str = "gate",
    pods: int = 24,
    services: int = 6,
    edges: int = 40,
):
    """(kube msgs, Traffic) for one tenant: its own Simulator (own
    interner namespace — uids genuinely collide across tenants, which is
    the point), rate/mix personality by tenant index, optionally
    incident-transformed."""
    from alaz_tpu.events.intern import Interner

    cfg = SimulationConfig(
        seed=seed * 1_000 + tenant,
        pod_count=pods,
        service_count=services,
        edge_count=edges,
        edge_rate=_TENANT_RATES[tenant % len(_TENANT_RATES)],
        test_duration_s=float(n_windows),
        chunk_size=2_048,
        protocol_mix=_TENANT_MIXES[tenant % len(_TENANT_MIXES)],
    )
    sim = Simulator(cfg, interner=Interner())
    kube = sim.setup()
    traffic = base_traffic(sim)
    if incident is not None:
        traffic = make_incident(incident, seed, scale).apply(sim, traffic)
    return kube, traffic


def _submit_k8s_all(svc, tenant: int, msgs, timeout_s: float = 30.0) -> None:
    """Submit control messages with BACKPRESSURE: the k8s queue is
    bounded (default 1000 events) and an incident's registration burst
    (hot_key ships thousands of pod ADDs) must not silently lose pods —
    a lost ADD turns the pod's whole stream into filtered/not_pod.
    Bounded retry: a wedged service degrades to misattributed (still
    ledgered) rows, never a hung driver."""
    deadline = time.monotonic() + timeout_s
    for m in msgs:
        while not svc.submit_k8s(m, tenant=tenant):
            if time.monotonic() > deadline:
                log.warning(f"tenant {tenant}: k8s submit backpressure timeout")
                return
            time.sleep(0.002)


def _drain_k8s(svc, tenant: int, timeout_s: float = 10.0) -> None:
    """Control events must attribute before the data rows they gate
    (the replay_delivery fidelity rule, across the queue hop): wait for
    the tenant's k8s queue to fold. Bounded — a wedged fold degrades to
    misattributed (ledgered) rows, never a hung driver."""
    part = svc.partitions[tenant]
    deadline = time.monotonic() + timeout_s
    while part.k8s_queue.unfinished and time.monotonic() < deadline:
        time.sleep(0.005)


def deliver_tenant(
    svc,
    tenant: int,
    kube,
    traffic: Traffic,
    pace_scale: float = 0.0,
    wall0: Optional[float] = None,
) -> int:
    """Replay one tenant's stream through the Service submit surface
    (tenant-tagged — the same routing a tenant-tagged wire frame takes).
    ``pace_scale`` > 0 maps event time to wall time (0.2 = 5× compressed
    replay) so close→score latency measures a LIVE cadence instead of a
    flood; 0 slams everything (throughput mode). Returns pushed L7 rows
    — the tenant's conservation numerator."""
    _submit_k8s_all(svc, tenant, kube)
    _drain_k8s(svc, tenant)
    if traffic.tcp is not None and len(traffic.tcp):
        svc.submit_tcp(traffic.tcp, tenant=tenant)
    t_base = traffic.deliveries[0].t0 if traffic.deliveries else 0
    if wall0 is None:
        wall0 = time.monotonic()
    pushed = 0
    for d in traffic.deliveries:
        if pace_scale > 0.0:
            target = wall0 + (d.t0 - t_base) * 1e-9 * pace_scale
            now = time.monotonic()
            if target > now:
                time.sleep(min(target - now, 2.0))
        for kind, payload in d.pre:
            if kind == "k8s":
                _submit_k8s_all(svc, tenant, payload)
                _drain_k8s(svc, tenant)
            else:
                svc.submit_tcp(payload, tenant=tenant)
        svc.submit_l7(d.batch, tenant=tenant)
        pushed += len(d)
    return pushed


def _settle(svc, timeout_s: float = 60.0) -> None:
    """Drain → flush every tenant's open windows → drain the scorer.
    Two flush rounds: the first may emit windows whose scoring reveals
    late retries the second sweeps."""
    svc.drain(timeout_s=timeout_s)
    svc.flush_windows()
    svc.drain(timeout_s=timeout_s)
    svc.flush_windows()
    svc.drain(timeout_s=timeout_s)


@dataclass
class _TenantRun:
    pushed: int = 0
    windows: List[int] = field(default_factory=list)  # window_start_ms, arrival order
    latencies: List[float] = field(default_factory=list)
    emitted_rows: int = 0


def _run_service(
    tenant_traffic: Dict[int, tuple],
    tenants: int,
    seed: int,
    pace_scale: float,
    ingest_workers: int = 1,
    chaos: Optional[ChaosConfig] = None,
    chaos_tenant: Optional[int] = None,
    batch_windows: int = 4,
    settle_timeout_s: float = 60.0,
):
    """One Service run over ``tenant_traffic`` ({tenant: (kube,
    traffic)}); returns ({tenant: _TenantRun}, service) with the service
    already stopped (its ledgers/planes stay readable).

    ``chaos`` + ``chaos_tenant`` arm worker kills on ONE tenant's shard
    pool only (the perturbed fleet) — installed post-construction, so
    the clean tenants' partitions run exactly the wiring the solo
    baselines ran and the isolation gates stay meaningful under chaos."""
    from alaz_tpu.runtime.service import Service

    cfg = RuntimeConfig(
        tenants=tenants,
        ingest_workers=ingest_workers,
        score_batch_windows=batch_windows,
        # live drift detectors at replay scale: a 2-window trailing
        # reference so the perturbed tenant's incident is measurable
        # inside an 8-window run (the production default would spend
        # the whole run warming up); clean-traffic silence at this
        # setting is a tested property of the plane
        trace=TraceConfig(score_drift_windows=2),
    )
    svc = Service(
        config=cfg,
        model_state={"host": "feature_logits"},
        score_fn=host_score_fn,
        score_many_fn=host_score_many_fn,
        score_threshold=2.0,  # nothing annotates; no sink is wired anyway
    )
    if chaos is not None and chaos.enabled and chaos_tenant is not None:
        from alaz_tpu.chaos.injectors import WorkerChaos

        part = svc.partitions[chaos_tenant]
        if part.sharded is None:
            raise ValueError(
                "chaos worker kills need ingest_workers > 1 (the worker "
                "seam lives in the sharded pool)"
            )
        hook = WorkerChaos(
            seed=chaos.seed,
            crash_prob=chaos.worker_crash_prob,
            stall_prob=chaos.worker_stall_prob,
            stall_s=chaos.worker_stall_s,
            max_crashes=chaos.worker_max_crashes,
            # ≥1 kill per run: a "conservation THROUGH kills" gate that
            # can pass with zero crashes proves nothing (the chaos
            # suite's never-vacuous rule)
            ensure_crash=True,
        )
        # attach-once before any traffic flows: the worker loop reads
        # the hook per item off the pipeline attribute
        part.fault_hook = hook
        part.sharded.fault_hook = hook
    runs = {t: _TenantRun() for t in tenant_traffic}

    def observe(batch, tenant, lat):
        r = runs[tenant]
        r.windows.append(int(batch.window_start_ms))
        r.latencies.append(float(lat))
        r.emitted_rows += batch.aggregated_rows()

    svc.score_observer = observe
    svc.start()
    try:
        wall0 = time.monotonic()
        threads = []
        results: Dict[int, int] = {}
        for t, (kube, traffic) in tenant_traffic.items():

            def run(t=t, kube=kube, traffic=traffic):
                results[t] = deliver_tenant(
                    svc, t, kube, traffic, pace_scale=pace_scale, wall0=wall0
                )

            th = threading.Thread(target=run, name=f"tenant-driver-{t}", daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=300.0)
        _settle(svc, timeout_s=settle_timeout_s)
    finally:
        svc.stop()
    for t, pushed in results.items():
        runs[t].pushed = pushed
    return runs, svc


def _p99(vals: List[float]) -> float:
    return float(np.percentile(vals, 99)) if vals else 0.0


# ---------------------------------------------------------------------------
# The isolation scenario + report.
# ---------------------------------------------------------------------------


@dataclass
class TenancyReport:
    tenants: int
    seed: int
    perturbed: int
    incident: str
    findings: List[str] = field(default_factory=list)
    per_tenant: dict = field(default_factory=dict)
    combined: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "scenario": "multi_tenant_isolation",
            "tenants": self.tenants,
            "seed": self.seed,
            "perturbed": self.perturbed,
            "incident": self.incident,
            "scenario_findings": len(self.findings),
            "findings": self.findings,
            "per_tenant": self.per_tenant,
            "combined": self.combined,
        }


def run_isolation_scenario(
    tenants: int = 3,
    seed: int = 0,
    perturbed: Optional[int] = None,
    incident: str = "retry_storm",
    n_windows: int = 8,
    pace_scale: float = 0.2,
    ingest_workers: int = 1,
    chaos: Optional[ChaosConfig] = None,
) -> TenancyReport:
    """The ISSUE 14 isolation gate (module docstring): K tenants on one
    backend, one perturbed; clean tenants must hold latency vs their
    solo baselines, stay drift-silent, and conserve rows exactly.

    ``chaos`` arms worker kills on the PERTURBED tenant's shard pool
    only (requires ``ingest_workers > 1``) — incident + chaos on one
    fleet, with the clean fleets' latency/drift/conservation gates all
    STILL ON: the ISSUE 14 acceptance combination. The perturbed
    tenant's own latency, scores and drift may degrade freely
    (recorded, never gated)."""
    if perturbed is None:
        perturbed = tenants - 1
    rep = TenancyReport(
        tenants=tenants, seed=seed, perturbed=perturbed, incident=incident
    )

    tenant_traffic = {
        t: make_tenant_traffic(
            t, seed, n_windows,
            incident=incident if t == perturbed else None,
        )
        for t in range(tenants)
    }

    # solo baselines: each CLEAN tenant alone on a single-tenant service
    # with identical scorer config (and no chaos anywhere — the baseline
    # is the undisturbed fleet) — the latency reference the combined
    # run is judged against ("tenancy must not cost a clean fleet")
    solo_p99: Dict[int, float] = {}
    for t in range(tenants):
        if t == perturbed:
            continue
        kube, traffic = make_tenant_traffic(t, seed, n_windows)
        solo_runs, _ = _run_service(
            {0: (kube, traffic)}, 1, seed, pace_scale,
            ingest_workers=ingest_workers,
        )
        solo_p99[t] = _p99(solo_runs[0].latencies)

    runs, svc = _run_service(
        tenant_traffic, tenants, seed, pace_scale,
        ingest_workers=ingest_workers, chaos=chaos, chaos_tenant=perturbed,
    )

    crashes = sum(
        getattr(p.fault_hook, "crashes", 0) for p in svc.partitions
    )
    restarts = sum(
        p.sharded.worker_restarts
        for p in svc.partitions
        if p.sharded is not None
    )
    if chaos is not None and chaos.enabled and crashes and not restarts:
        rep.findings.append(
            f"isolation: {crashes} worker crashes injected but no restart "
            "observed — supervision dead under tenancy"
        )

    for t in range(tenants):
        r = runs[t]
        part = svc.partitions[t]
        ledger = part.ledger.snapshot()
        gap = r.pushed - r.emitted_rows - ledger["total"]
        plane = svc.tenant_scores(t)
        drift_events = plane.drift_events if plane is not None else 0
        p99 = _p99(r.latencies)
        entry = {
            "pushed": r.pushed,
            "emitted_rows": r.emitted_rows,
            "windows": len(r.windows),
            "ledger": ledger,
            "gap": int(gap),
            "p99_close_to_score_ms": round(p99 * 1e3, 2),
            "drift_events": drift_events,
            "perturbed": t == perturbed,
        }
        if t in solo_p99:
            entry["solo_p99_close_to_score_ms"] = round(solo_p99[t] * 1e3, 2)
        rep.per_tenant[str(t)] = entry
        if gap != 0:
            rep.findings.append(
                f"isolation: tenant {t} conservation broken — "
                f"pushed={r.pushed} emitted={r.emitted_rows} "
                f"ledger={ledger} gap={gap}"
            )
        if any(b <= a for a, b in zip(r.windows, r.windows[1:])):
            rep.findings.append(
                f"isolation: tenant {t} windows not strictly ascending: "
                f"{r.windows}"
            )
        if not r.windows:
            rep.findings.append(
                f"isolation: tenant {t} emitted no windows — vacuous run"
            )
        if t == perturbed:
            continue  # the perturbed tenant may degrade: recorded above
        if drift_events:
            rep.findings.append(
                f"isolation: clean tenant {t} drift detector paged "
                f"({drift_events} events) during a neighbor's incident — "
                "cross-tenant score contamination"
            )
        if t in solo_p99:
            bound = max(
                solo_p99[t] * LATENCY_RATIO, solo_p99[t] + LATENCY_FLOOR_S
            )
            if p99 > bound:
                rep.findings.append(
                    f"isolation: clean tenant {t} p99 close-to-score "
                    f"{p99*1e3:.1f}ms exceeds its solo baseline "
                    f"{solo_p99[t]*1e3:.1f}ms bound (+10% / +"
                    f"{LATENCY_FLOOR_S*1e3:.0f}ms floor) — head-of-line "
                    "interference from the perturbed tenant"
                )

    rep.combined = {
        "windows": svc.scored_batches,
        "dispatches": svc.score_dispatches,
        "multi_tenant_groups": svc.multi_tenant_groups,
        "group_occupancy": round(
            svc.scored_batches / svc.score_dispatches, 3
        )
        if svc.score_dispatches
        else 0.0,
        "worker_crashes": crashes,
        "worker_restarts": restarts,
    }
    for f in rep.findings:
        log.warning(f"isolation finding: {f}")
    return rep


# ---------------------------------------------------------------------------
# Bench leg (bench.py --ingest --tenants K): unpaced throughput record.
# ---------------------------------------------------------------------------


def tenant_serving_bench(
    tenants: int,
    n_rows: int = 262_144,
    windows: int = 8,
    seed: int = 0,
    batch_windows: int = 4,
) -> dict:
    """Unpaced K-tenant serving throughput: one synthetic trace split
    round-robin across K fleets (disjoint row slices, shared k8s
    topology folded into each tenant's own namespace), slammed through
    the tenancy plane. Reports aggregate windows/s and rows/s,
    per-tenant p99 close→score latency, and the cross-tenant batching
    occupancy (mean windows per scorer dispatch — K serial backends
    would sit at 1.0)."""
    from alaz_tpu.replay.synth import make_ingest_trace
    from alaz_tpu.runtime.service import Service

    ev, msgs = make_ingest_trace(n_rows, windows=windows, seed=seed)
    cfg = RuntimeConfig(
        tenants=tenants,
        score_batch_windows=batch_windows,
        trace=TraceConfig(score_drift_windows=4),
    )
    svc = Service(
        config=cfg,
        model_state={"host": "feature_logits"},
        score_fn=host_score_fn,
        score_many_fn=host_score_many_fn,
        score_threshold=2.0,
    )
    lat: Dict[int, List[float]] = {t: [] for t in range(tenants)}
    scored_rows = [0]

    def observe(batch, tenant, l):
        lat[tenant].append(float(l))
        scored_rows[0] += batch.aggregated_rows()

    svc.score_observer = observe
    svc.start()
    try:
        for t in range(tenants):
            _submit_k8s_all(svc, t, msgs)
        for t in range(tenants):
            _drain_k8s(svc, t)
        slices = [ev[t::tenants] for t in range(tenants)]
        chunk = 1 << 14
        t0 = time.perf_counter()
        # round-robin across tenants chunk by chunk: the interleaving a
        # real fleet of agents produces, and what fills cross-tenant
        # groups (K same-bucket windows close near-simultaneously)
        offsets = [0] * tenants
        live = True
        while live:
            live = False
            for t in range(tenants):
                sl = slices[t]
                o = offsets[t]
                if o < sl.shape[0]:
                    svc.submit_l7(sl[o : o + chunk], tenant=t)
                    offsets[t] = o + chunk
                    live = True
        _settle(svc, timeout_s=120.0)
        wall = time.perf_counter() - t0
    finally:
        svc.stop()
    windows_scored = svc.scored_batches
    return {
        "tenants": tenants,
        "rows": n_rows,
        "windows_scored": windows_scored,
        "windows_per_sec": round(windows_scored / wall, 2) if wall > 0 else 0.0,
        "rows_per_sec": round(n_rows / wall) if wall > 0 else 0,
        "scored_rows": scored_rows[0],
        "wall_s": round(wall, 3),
        "dispatches": svc.score_dispatches,
        "multi_tenant_groups": svc.multi_tenant_groups,
        # the cross-tenant batching headline: mean windows per dispatch
        # (K serial backends = 1.0; the shared backend packs K fleets'
        # same-bucket close waves into one arena fill)
        "group_occupancy": round(
            windows_scored / svc.score_dispatches, 3
        )
        if svc.score_dispatches
        else 0.0,
        "per_tenant_p99_ms": {
            str(t): round(_p99(v) * 1e3, 2) for t, v in lat.items()
        },
        "per_tenant_ledger": {
            str(p.tenant): p.ledger.snapshot()["total"] for p in svc.partitions
        },
    }
