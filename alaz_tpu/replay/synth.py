"""Synthetic host-ingest traces — the ONE trace definition shared by
``bench.py --ingest``, ``tools/profile_ingest.py``, the perf smoke
test, the sharded-equivalence suite and the chaos harness
(alaz_tpu/chaos), so every consumer drives the identical row stream.

Lived in bench.py through ISSUE 5; moved into the package in ISSUE 6 so
the chaos harness (a package module) doesn't import the repo-root bench
script — bench.py re-exports it, existing imports keep working.
"""

from __future__ import annotations


def make_ingest_trace(
    n_rows: int,
    pods: int = 500,
    svcs: int = 50,
    outbound_ips: int = 200,
    paths: int = 64,
    windows: int = 8,
    seed: int = 0,
):
    """Synthetic L7 trace for the host-ingest microbench: V2 events with
    embedded addresses (pod sources; half service, half outbound
    destinations) and a bounded set of unique HTTP payloads.

    Returns (events, cluster_msgs): feed the msgs into a ClusterInfo and
    the events through Aggregator.process_l7. Every event attributes
    (all sources are known pods), so downstream row-conservation checks
    can equate pushed rows with emitted + ledgered rows.
    """
    import numpy as np

    from alaz_tpu.events.k8s import EventType, K8sResourceMessage, Pod, ResourceType, Service
    from alaz_tpu.events.net import ip_to_u32
    from alaz_tpu.events.schema import HttpMethod, L7Protocol, make_l7_events

    rng = np.random.default_rng(seed)
    msgs = []
    pod_ips = np.empty(pods, dtype=np.uint32)
    for p in range(pods):
        ip = f"10.{(p >> 16) & 0xFF}.{(p >> 8) & 0xFF}.{p & 0xFF}"
        pod_ips[p] = ip_to_u32(ip)
        msgs.append(
            K8sResourceMessage(
                ResourceType.POD, EventType.ADD, Pod(uid=f"pod-{p}", name=f"p{p}", ip=ip)
            )
        )
    svc_ips = np.empty(svcs, dtype=np.uint32)
    for s in range(svcs):
        ip = f"10.96.{(s >> 8) & 0xFF}.{s & 0xFF}"
        svc_ips[s] = ip_to_u32(ip)
        msgs.append(
            K8sResourceMessage(
                ResourceType.SERVICE, EventType.ADD,
                Service(uid=f"svc-{s}", name=f"s{s}", cluster_ip=ip),
            )
        )
    # outbound destinations: third-party IPs the cluster tables don't know
    out_ips = (
        np.uint32(ip_to_u32("52.0.0.1")) + rng.permutation(1 << 16)[:outbound_ips].astype(np.uint32)
    )

    ev = make_l7_events(n_rows)
    ev["pid"] = rng.integers(1000, 1000 + pods, n_rows)
    ev["fd"] = rng.integers(3, 500, n_rows)
    # event time advances through `windows` one-second windows so window
    # closes interleave with ingest (the watermark path, not just flush)
    ev["write_time_ns"] = 1_000_000_000 + (
        np.arange(n_rows, dtype=np.uint64) * np.uint64(windows) * np.uint64(1_000_000_000)
    ) // np.uint64(max(n_rows, 1))
    ev["duration_ns"] = rng.integers(10_000, 5_000_000, n_rows)
    ev["protocol"] = L7Protocol.HTTP
    ev["method"] = HttpMethod.GET
    ev["status"] = np.where(rng.random(n_rows) < 0.05, 500, 200)
    ev["saddr"] = pod_ips[rng.integers(0, pods, n_rows)]
    ev["sport"] = rng.integers(1024, 65535, n_rows)
    # destination mix: ~half in-cluster services, ~half outbound (the
    # outbound half is what exercises the reverse-DNS intern path)
    is_out = rng.random(n_rows) < 0.5
    daddr = svc_ips[rng.integers(0, svcs, n_rows)]
    daddr[is_out] = out_ips[rng.integers(0, outbound_ips, int(is_out.sum()))]
    ev["daddr"] = daddr
    ev["dport"] = np.where(is_out, 443, 80)
    # bounded unique-payload set: the hashed-parse cache amortizes parsing,
    # so path enrichment is per-unique, as in production
    path_idx = rng.integers(0, paths, n_rows)
    for p in range(paths):
        payload = f"GET /api/v1/resource{p} HTTP/1.1\r\nHost: bench\r\n\r\n".encode()
        rows_p = np.flatnonzero(path_idx == p)
        buf = np.frombuffer(payload, dtype=np.uint8)
        ev["payload"][rows_p[:, None], np.arange(buf.shape[0])[None, :]] = buf
        ev["payload_size"][rows_p] = len(payload)
    return ev, msgs
