"""Replay/simulation harness — the main_benchmark_test.go test plane.

Every BASELINE.json config is a replay: a deterministic generator fabricates
k8s metadata, TCP establishes, and rate-shaped L7 traffic (the Simulator
analog, main_benchmark_test.go:311-633), which flows through the real
aggregator into any DataStore sink. Traces can also be saved/loaded as NPZ
for byte-identical replays.
"""

from alaz_tpu.replay.simulator import Simulator, ReplayResult, run_replay
from alaz_tpu.replay.trace import save_trace, load_trace

__all__ = ["Simulator", "ReplayResult", "run_replay", "save_trace", "load_trace"]
