"""Trace persistence: save/replay columnar event streams as NPZ.

The reference has no trace format (its tests re-fabricate traffic each
run); recorded traces make replays byte-identical across the CPU-reference
and TPU paths — the parity requirement in SURVEY §7 hard part (e).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterator, List

import numpy as np

from alaz_tpu.events.k8s import (
    EventType,
    K8sResourceMessage,
    Pod,
    ResourceType,
    Service,
)

_RESOURCE_CLASSES = {"Pod": Pod, "Service": Service}


def save_trace(
    path: str | Path,
    kube_msgs: List[K8sResourceMessage],
    tcp_events: np.ndarray,
    l7_batches: Iterator[np.ndarray],
) -> None:
    path = Path(path)
    l7 = list(l7_batches)
    l7_all = np.concatenate(l7) if l7 else np.zeros(0)
    kube_json = json.dumps(
        [
            {
                "resource_type": m.resource_type.value,
                "event_type": m.event_type.value,
                "kind": type(m.object).__name__,
                "object": m.object.__dict__,
            }
            for m in kube_msgs
            if type(m.object).__name__ in _RESOURCE_CLASSES
        ]
    )
    np.savez_compressed(
        path,
        tcp=tcp_events,
        l7=l7_all,
        kube=np.frombuffer(kube_json.encode(), dtype=np.uint8),
    )


def load_trace(path: str | Path):
    """→ (kube_msgs, tcp_events, l7_events)."""
    with np.load(path) as z:
        kube_json = bytes(z["kube"]).decode()
        tcp = z["tcp"]
        l7 = z["l7"]
    msgs = []
    for item in json.loads(kube_json):
        cls = _RESOURCE_CLASSES[item["kind"]]
        obj = cls(**item["object"])
        msgs.append(
            K8sResourceMessage(
                ResourceType(item["resource_type"]),
                EventType(item["event_type"]),
                obj,
            )
        )
    return msgs, tcp, l7
