"""Incident scenario library — the graph shapes that break service maps.

PR 6's chaos harness covered *delivery* faults (corrupt frames, crashed
workers, backend brownouts). This module covers the *semantic* shapes —
the traffic patterns production incidents draw on the service graph:

- ``deploy_rollout``     — mass pod churn re-keying half the node table
                           (DELETE + replacement ADDs mid-stream; traffic
                           continues from the replacements' new IPs).
- ``dns_storm``          — a burst of lookups fanning out to thousands of
                           UNIQUE outbound destinations (the reverse-DNS
                           naming / interner / node-table growth path).
- ``hot_key``            — one destination with in-degree up to 500k
                           (fan-in collapse; survivable only with
                           degree-capped sampling, graph/builder.py).
- ``retry_storm``        — a victim service 5xx's and its callers retry:
                           correlated error-amplifying fan-out, load
                           multiplying on the victim AND the callers'
                           other dependencies.
- ``backpressure_wave``  — bursty rate with stalls: k windows of traffic
                           compressed into one, delivered as jumbo
                           batches (the post-stall buffer dump).

Every incident is a seed-driven **composable transform** over the
existing :class:`~alaz_tpu.replay.simulator.Simulator` traffic: it takes
a :class:`Traffic` (topology + TCP establishes + an ordered stream of
:class:`Delivery` items) and returns a perturbed one, so
``hot_key ∘ backpressure_wave`` is just two ``apply`` calls, and the
PR 6 chaos seams compose at the delivery plane (``BatchChaos.perturb``
operates on the same Delivery stream; ``run_anomaly_scenario(incident=,
chaos=)`` makes "hot-key during a degraded delivery" one line).

Each scenario's **eval record** (:class:`ScenarioReport`) is gated on
three invariants:

1. *detection holds* — blended AUROC within tolerance of the clean gate
   (the detection leg trains on scenario-shaped traffic);
2. *the host plane holds rate* — bounded flush/drain, EXACT row
   conservation through the drop ledger (now including the ``sampled``
   cause the degree cap attributes to);
3. *windows stay exactly-once* — strictly ascending emission, no window
   emitted twice.

``python -m alaz_tpu.replay`` (= ``make scenarios``) sweeps fixed seeds
over every scenario; ``bench.py --scenario NAME`` records one scenario's
rows/s + p99 close latency + ledger breakdown + AUROC, and ``bench.py
--ingest`` runs the host gates for all scenarios every round
(``scenario_findings``, expected 0).
"""

from __future__ import annotations

import bisect
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from alaz_tpu.config import ChaosConfig, SimulationConfig
from alaz_tpu.events.k8s import (
    EventType,
    K8sResourceMessage,
    Pod,
    ResourceType,
)
from alaz_tpu.events.net import ip_to_u32, u32_to_ip
from alaz_tpu.events.schema import make_l7_events
from alaz_tpu.logging import get_logger
from alaz_tpu.replay.simulator import Simulator

log = get_logger("alaz_tpu.incidents")

SCENARIO_NAMES = (
    "deploy_rollout",
    "dns_storm",
    "hot_key",
    "retry_storm",
    "backpressure_wave",
)

_WINDOW_NS = 1_000_000_000  # scenario traffic runs at window_s = 1.0


class Delivery:
    """One L7 batch plus the control events that must land before it.

    Attaching topology (k8s) and establish (tcp) events to the batch
    they gate — instead of interleaving bare control items — is what
    makes the stream safely perturbable: chaos duplication/reordering
    moves a batch WITH its prerequisites (k8s ADDs are idempotent), so
    a hot-key batch never outruns its pods' registrations by more than
    the adjacent-swap the chaos plane is allowed.

    ``__len__`` is the ROW count, the contract BatchChaos and the
    bounded queues already key on."""

    __slots__ = ("pre", "batch")

    def __init__(self, batch: np.ndarray, pre: Optional[list] = None):
        self.pre = pre if pre is not None else []  # [("k8s", msgs) | ("tcp", ev)]
        self.batch = batch

    def __len__(self) -> int:
        return int(self.batch.shape[0])

    @property
    def t0(self) -> int:
        return int(self.batch["write_time_ns"][0]) if len(self) else 0


@dataclass
class Traffic:
    """A scenario's full input stream: initial topology + establishes +
    the time-ordered delivery stream, plus the labeling the incident
    contributes to the detection oracle (pairs it made anomalous)."""

    kube: List[K8sResourceMessage]
    tcp: np.ndarray
    deliveries: List[Delivery]
    # (from_uid_id, to_uid_id) pairs the incident makes anomalous, and
    # the [start_ms, end_ms) span they are anomalous in — composed into
    # the detection oracle next to the fault plan's labels
    label_pairs: Set[Tuple[int, int]] = field(default_factory=set)
    label_span_ms: Tuple[int, int] = (0, 1 << 62)
    meta: Dict = field(default_factory=dict)

    @property
    def total_rows(self) -> int:
        return sum(len(d) for d in self.deliveries)


def base_traffic(sim: Simulator) -> Traffic:
    """Wrap a set-up Simulator's stream as the identity Traffic every
    incident transforms. ``sim.setup()`` must have run."""
    return Traffic(
        kube=[],  # callers fold sim.setup()'s messages themselves
        tcp=sim.tcp_events(),
        deliveries=[Delivery(b) for b in sim.iter_l7_batches()],
    )


def _insert_by_time(deliveries: List[Delivery], extra: List[Delivery]) -> List[Delivery]:
    """Merge extra deliveries into a time-ordered stream by first-row
    timestamp (stable: base traffic keeps its order)."""
    keys = [d.t0 for d in deliveries]
    out = list(deliveries)
    offset = 0
    for d in sorted(extra, key=lambda d: d.t0):
        pos = bisect.bisect_right(keys, d.t0)
        out.insert(pos + offset, d)
        offset += 1
    return out


def flatten_sorted(traffic: Traffic, chunk: int = 4096) -> Traffic:
    """Row-level time-sorted re-chunking of the delivery stream, with
    every control event moved up front. ``_insert_by_time`` orders
    deliveries only by their FIRST row, so overlapping batches (a hot
    key's fan-in interleaving with base traffic) deliver rows out of
    order — realistic, and exactly what the conservation gates are for.
    The EXACTNESS equivalence tests (serial == sharded, bit for bit)
    need the in-order shape instead: close timing is a documented
    degree of freedom between the two stores, and only an in-order
    stream removes it."""
    if not traffic.deliveries:
        return traffic
    pre = [p for d in traffic.deliveries for p in d.pre]
    allb = np.concatenate([d.batch for d in traffic.deliveries])
    allb = allb[np.argsort(allb["write_time_ns"], kind="stable")]
    deliveries = [
        Delivery(allb[i : i + chunk]) for i in range(0, allb.shape[0], chunk)
    ]
    deliveries[0].pre = pre
    return Traffic(
        kube=traffic.kube,
        tcp=traffic.tcp,
        deliveries=deliveries,
        label_pairs=traffic.label_pairs,
        label_span_ms=traffic.label_span_ms,
        meta=traffic.meta,
    )


def replay_delivery(target, d: Delivery, now_ns: Optional[int] = None) -> int:
    """Replay one Delivery into an aggregator-shaped ``target``
    (``process_k8s``/``process_tcp``/``process_l7``): its prerequisite
    control events first, then the L7 batch stamped at its own write
    horizon (or an explicit ``now_ns`` — how late deliveries land past
    a sealed watermark). Returns the batch's write horizon, so drivers
    can track the stream's high-water mark."""
    for kind, payload in d.pre:
        if kind == "k8s":
            # control events must not race ahead of queued data rows:
            # the sharded pipeline folds k8s synchronously while L7
            # rows may still sit in shard queues, so a rollout's pod
            # DELETE would apply BEFORE the pod's earlier traffic
            # attributes (its pre-cut rows all drop as not_pod and the
            # pod never appears in any emitted window). Stream position
            # is the contract — drain the data plane first. Serial
            # targets process synchronously and have no drain: no-op.
            drain = getattr(target, "drain", None)
            if drain is not None:
                drain(timeout_s=10.0)
            for m in payload:
                target.process_k8s(m)
        else:
            target.process_tcp(payload)
    end = int(d.batch["write_time_ns"][-1])
    target.process_l7(d.batch, now_ns=end if now_ns is None else now_ns)
    return end


def _edge_key_table(sim: Simulator):
    """(sorted conn keys, svc_ip_u32 per key, pod_idx per key, svc_idx
    per key) — the vectorized row→edge resolver incidents use to rewrite
    or amplify traffic on chosen edges."""
    keys = np.array(
        [(e.pid << 32) | e.fd for e in sim.edges], dtype=np.uint64
    )
    svc_ip = np.array(
        [ip_to_u32(sim.services[e.svc_idx].cluster_ip) for e in sim.edges],
        dtype=np.uint32,
    )
    pod_idx = np.array([e.pod_idx for e in sim.edges], dtype=np.int64)
    svc_idx = np.array([e.svc_idx for e in sim.edges], dtype=np.int64)
    order = np.argsort(keys)
    return keys[order], svc_ip[order], pod_idx[order], svc_idx[order]


def _row_edge_lookup(batch: np.ndarray, sorted_keys: np.ndarray) -> np.ndarray:
    """Index into the sorted edge-key table for every row (conn-key
    join); rows with no edge get -1."""
    rk = (batch["pid"].astype(np.uint64) << np.uint64(32)) | batch["fd"].astype(
        np.uint64
    )
    pos = np.searchsorted(sorted_keys, rk)
    pos = np.minimum(pos, sorted_keys.shape[0] - 1)
    hit = sorted_keys[pos] == rk
    return np.where(hit, pos, -1)


class Incident:
    """Base incident: a named, seed-driven transform over Traffic."""

    name = "incident"

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        # per-incident stream: (name hash, seed) so composed incidents
        # with the same seed still draw independently; crc32, not
        # hash() — PYTHONHASHSEED randomizes str hashes per process and
        # the fixed-seed gates promise cross-run reproducibility
        self.rng = np.random.default_rng(
            (zlib.crc32(self.name.encode()), int(seed))
        )

    def apply(self, sim: Simulator, traffic: Traffic) -> Traffic:
        raise NotImplementedError


class HotKey(Incident):
    """One destination service accumulates in-degree ``fan_in``: that
    many NEW pods each send ``reqs_per_src`` requests into it inside
    ``hot_windows``. V2 events (addresses embedded) so the fan-in needs
    no socket state — exactly how a thundering herd looks to the agent.

    This is the scenario the degree cap exists for: uncapped, every hot
    window becomes a fan_in-row batch (bucket-ladder top rung, close
    stall); capped, the dst keeps its true in-degree signal in the node
    features while its edge list is bounded."""

    name = "hot_key"

    def __init__(
        self,
        seed: int = 0,
        fan_in: int = 8_000,
        hot_windows: Sequence[int] = (2, 3),
        reqs_per_src: int = 1,
        chunk: int = 1 << 16,
    ):
        super().__init__(seed)
        self.fan_in = int(fan_in)
        self.hot_windows = tuple(hot_windows)
        self.reqs_per_src = int(reqs_per_src)
        self.chunk = int(chunk)

    def apply(self, sim: Simulator, traffic: Traffic) -> Traffic:
        svc = sim.services[int(self.rng.integers(0, len(sim.services)))]
        svc_ip = ip_to_u32(svc.cluster_ip)
        n = self.fan_in
        base_ip = ip_to_u32("11.0.0.0")
        ips = base_ip + 1 + np.arange(n, dtype=np.uint64)
        msgs = [
            K8sResourceMessage(
                ResourceType.POD,
                EventType.ADD,
                Pod(
                    uid=f"hk-pod-{self.seed}-{i}",
                    name=f"hk{i}",
                    namespace="hot",
                    ip=u32_to_ip(int(ips[i])),
                ),
            )
            for i in range(n)
        ]
        t_base = int(traffic.deliveries[0].t0) if traffic.deliveries else 0
        w0 = t_base // _WINDOW_NS
        total = n * self.reqs_per_src
        src = np.tile(np.arange(n, dtype=np.int64), self.reqs_per_src)
        win = np.asarray(self.hot_windows, dtype=np.int64)[
            self.rng.integers(0, len(self.hot_windows), total)
        ]
        ts = (
            (w0 + win) * _WINDOW_NS
            + self.rng.integers(0, _WINDOW_NS, total)
        ).astype(np.uint64)
        order = np.argsort(ts, kind="stable")
        src, ts = src[order], ts[order]
        extra: List[Delivery] = []
        for lo in range(0, total, self.chunk):
            hi = min(lo + self.chunk, total)
            ev = make_l7_events(hi - lo)
            s = src[lo:hi]
            ev["pid"] = (3_000_000 + s).astype(np.uint32)
            ev["fd"] = 7
            ev["write_time_ns"] = ts[lo:hi]
            ev["duration_ns"] = self.rng.integers(20_000, 400_000, hi - lo)
            ev["protocol"] = 1  # HTTP
            ev["method"] = 1
            ev["status"] = 200
            ev["saddr"] = ips[s].astype(np.uint32)
            ev["sport"] = (20_000 + (s % 40_000)).astype(np.uint16)
            ev["daddr"] = np.uint32(svc_ip)
            ev["dport"] = 80
            pre = [("k8s", msgs)] if lo == 0 else []
            extra.append(Delivery(ev, pre=pre))
        traffic.deliveries = _insert_by_time(traffic.deliveries, extra)
        traffic.meta["hot_key"] = {
            "svc_uid": svc.uid,
            "fan_in": n,
            "hot_windows": [int(w0 + w) for w in self.hot_windows],
            "rows": int(total),
        }
        return traffic


class DeployRollout(Incident):
    """Mass pod churn: at window ``at_window``, ``churn_frac`` of the
    pods are DELETEd and replaced by new uids on new IPs — re-keying
    that half of the node table — and their edges' traffic continues
    from the replacements (rewritten to V2 rows carrying the new
    addresses, as a re-scheduled pod's connections would)."""

    name = "deploy_rollout"

    def __init__(self, seed: int = 0, churn_frac: float = 0.5, at_window: int = 2):
        super().__init__(seed)
        self.churn_frac = float(churn_frac)
        self.at_window = int(at_window)

    def apply(self, sim: Simulator, traffic: Traffic) -> Traffic:
        n_pods = len(sim.pods)
        n_churn = max(1, int(n_pods * self.churn_frac))
        churned = self.rng.choice(n_pods, size=n_churn, replace=False)
        churn_mask = np.zeros(n_pods, dtype=bool)
        churn_mask[churned] = True
        new_ip = np.zeros(n_pods, dtype=np.uint32)
        base_ip = ip_to_u32("13.0.0.0")
        msgs: List[K8sResourceMessage] = []
        for j, p in enumerate(churned):
            old = sim.pods[int(p)]
            ip = int(base_ip + 1 + j)
            new_ip[p] = ip
            msgs.append(K8sResourceMessage(ResourceType.POD, EventType.DELETE, old))
            msgs.append(
                K8sResourceMessage(
                    ResourceType.POD,
                    EventType.ADD,
                    Pod(
                        uid=f"{old.uid}-r1",
                        name=f"{old.name}-r1",
                        namespace=old.namespace,
                        image=old.image,
                        ip=u32_to_ip(ip),
                    ),
                )
            )
        keys, svc_ip, pod_idx, _svc = _edge_key_table(sim)
        t_base = int(traffic.deliveries[0].t0) if traffic.deliveries else 0
        t_cut = ((t_base // _WINDOW_NS) + self.at_window) * _WINDOW_NS
        rolled = False
        rewritten = 0
        out_deliveries: List[Delivery] = []
        for d in traffic.deliveries:
            b = d.batch
            after = b["write_time_ns"] >= np.uint64(t_cut)
            if not after.any():
                out_deliveries.append(d)
                continue
            if not rolled:
                if not after.all():
                    # the chunk straddles the cut: split it so the
                    # DELETE+ADD lands exactly at the rollout's window
                    # boundary — attached to the straddling chunk it
                    # would apply mid-window and cut the victims' rows
                    # HALF a window early (a phantom perturbation the
                    # drift monitor rightly paged on)
                    out_deliveries.append(Delivery(b[~after], pre=d.pre))
                    d = Delivery(b[after], pre=[("k8s", msgs)])
                    b = d.batch
                    after = b["write_time_ns"] >= np.uint64(t_cut)
                else:
                    d.pre.append(("k8s", msgs))
                rolled = True
            out_deliveries.append(d)
            eidx = _row_edge_lookup(b, keys)
            hit = after & (eidx >= 0)
            if hit.any():
                pi = pod_idx[eidx[hit]]
                sub = hit.copy()
                sub[hit] = churn_mask[pi]
                if sub.any():
                    pi = pod_idx[eidx[sub]]
                    # V2 rewrite: the replacement pod's address + the
                    # edge's service address (re-established connection)
                    b["saddr"][sub] = new_ip[pi]
                    b["daddr"][sub] = svc_ip[eidx[sub]]
                    b["dport"][sub] = 80
                    rewritten += int(sub.sum())
        traffic.deliveries = out_deliveries
        traffic.meta["deploy_rollout"] = {
            "churned_pods": int(n_churn),
            "rewritten_rows": rewritten,
            "cut_ms": t_cut // 1_000_000,
        }
        return traffic


class DnsStorm(Incident):
    """A lookup storm: existing pods fan out to ``n_names`` UNIQUE
    outbound destinations over ``storm_windows``, ``rows_per_window``
    rows per window — the reverse-DNS naming + interner + node-table
    growth stress (every unique address becomes a named outbound node)."""

    name = "dns_storm"

    def __init__(
        self,
        seed: int = 0,
        n_names: int = 2_000,
        storm_windows: Sequence[int] = (2, 3),
        rows_per_window: int = 8_000,
    ):
        super().__init__(seed)
        self.n_names = int(n_names)
        self.storm_windows = tuple(storm_windows)
        self.rows_per_window = int(rows_per_window)

    def apply(self, sim: Simulator, traffic: Traffic) -> Traffic:
        pod_ips = np.array(
            [ip_to_u32(p.ip) for p in sim.pods], dtype=np.uint32
        )
        out_ips = (
            np.uint64(ip_to_u32("52.40.0.0"))
            + 1
            + self.rng.permutation(1 << 22)[: self.n_names].astype(np.uint64)
        ).astype(np.uint32)
        t_base = int(traffic.deliveries[0].t0) if traffic.deliveries else 0
        w0 = t_base // _WINDOW_NS
        extra: List[Delivery] = []
        rows = 0
        for w in self.storm_windows:
            k = self.rows_per_window
            ev = make_l7_events(k)
            ev["pid"] = (
                1000 + self.rng.integers(0, len(sim.pods), k)
            ).astype(np.uint32)
            ev["fd"] = (900_000 + np.arange(k)).astype(np.uint64)
            ev["write_time_ns"] = (
                (w0 + w) * _WINDOW_NS + self.rng.integers(0, _WINDOW_NS, k)
            ).astype(np.uint64)
            ev["write_time_ns"].sort()
            ev["duration_ns"] = self.rng.integers(5_000, 80_000, k)
            ev["protocol"] = 0  # UNKNOWN: lookup traffic, no L7 enrichment
            ev["status"] = 0
            ev["saddr"] = pod_ips[self.rng.integers(0, pod_ips.shape[0], k)]
            ev["sport"] = 30_000
            ev["daddr"] = out_ips[self.rng.integers(0, out_ips.shape[0], k)]
            ev["dport"] = 53
            extra.append(Delivery(ev))
            rows += k
        traffic.deliveries = _insert_by_time(traffic.deliveries, extra)
        traffic.meta["dns_storm"] = {
            "unique_names": self.n_names,
            "rows": rows,
        }
        return traffic


class RetryStorm(Incident):
    """Correlated error-amplifying fan-out: a victim service starts
    5xx'ing inside ``storm_windows``; every request to it is retried
    ``amp``× (load multiplies on the victim edges), and the callers —
    now spending their budgets on retries — also push ``caller_amp``×
    extra load onto their OTHER dependencies (the cascade that turns
    one bad service into a map-wide brownout). The victim edges are the
    incident's labeled anomaly."""

    name = "retry_storm"

    def __init__(
        self,
        seed: int = 0,
        amp: int = 4,
        caller_amp: int = 2,
        storm_windows: Sequence[int] = (2, 3, 4),
    ):
        super().__init__(seed)
        self.amp = max(1, int(amp))
        self.caller_amp = max(1, int(caller_amp))
        self.storm_windows = tuple(storm_windows)

    def apply(self, sim: Simulator, traffic: Traffic) -> Traffic:
        keys, _svc_ip, pod_idx, svc_idx = _edge_key_table(sim)
        # victim: the service with the most incoming edges (the shared
        # dependency a real retry storm converges on), rng tie-broken
        counts = np.bincount(svc_idx, minlength=len(sim.services)).astype(float)
        counts += self.rng.random(counts.shape[0]) * 0.5
        victim = int(np.argmax(counts))
        victim_edge = svc_idx == victim
        caller_pods = np.unique(pod_idx[victim_edge])
        caller_other = np.isin(pod_idx, caller_pods) & ~victim_edge
        t_base = int(traffic.deliveries[0].t0) if traffic.deliveries else 0
        w0 = t_base // _WINDOW_NS
        span = (
            np.int64(min(self.storm_windows) + w0) * _WINDOW_NS,
            np.int64((max(self.storm_windows) + w0 + 1)) * _WINDOW_NS,
        )
        out: List[Delivery] = []
        amped = 0
        for d in traffic.deliveries:
            b = d.batch
            ts = b["write_time_ns"]
            in_span = (ts >= np.uint64(span[0])) & (ts < np.uint64(span[1]))
            if not in_span.any():
                out.append(d)
                continue
            eidx = _row_edge_lookup(b, keys)
            vic = in_span & (eidx >= 0)
            vic[vic] = victim_edge[eidx[vic]]
            cal = in_span & (eidx >= 0)
            cal[cal] = caller_other[eidx[cal]]
            b["status"][vic] = 503  # the victim is failing
            parts = [b]
            if vic.any():
                retries = np.repeat(b[vic], self.amp - 1) if self.amp > 1 else None
                if retries is not None and retries.shape[0]:
                    retries["write_time_ns"] += self.rng.integers(
                        1_000, 50_000_000, retries.shape[0]
                    ).astype(np.uint64)
                    parts.append(retries)
                    amped += retries.shape[0]
            if cal.any() and self.caller_amp > 1:
                fanout = np.repeat(b[cal], self.caller_amp - 1)
                if fanout.shape[0]:
                    fanout["write_time_ns"] += self.rng.integers(
                        1_000, 50_000_000, fanout.shape[0]
                    ).astype(np.uint64)
                    parts.append(fanout)
                    amped += fanout.shape[0]
            if len(parts) > 1:
                merged = np.concatenate(parts)
                merged = merged[np.argsort(merged["write_time_ns"], kind="stable")]
                out.append(Delivery(merged, pre=d.pre))
            else:
                out.append(d)
        traffic.deliveries = out
        # labeled anomaly: every (pod, victim) pair, over the storm span
        vuid = sim.interner.intern(sim.services[victim].uid)
        for e in sim.edges:
            if e.svc_idx == victim:
                traffic.label_pairs.add(
                    (sim.interner.intern(sim.pods[e.pod_idx].uid), vuid)
                )
        traffic.label_span_ms = (int(span[0] // 1_000_000), int(span[1] // 1_000_000))
        traffic.meta["retry_storm"] = {
            "victim_uid": sim.services[victim].uid,
            "amplified_rows": int(amped),
            "victim_edges": int(victim_edge.sum()),
        }
        return traffic


class BackpressureWave(Incident):
    """Bursty rate with stalls: every run of ``compress`` windows
    collapses into its first window (the agent buffered through a
    stall, then dumped), and runs of ``jumbo`` consecutive deliveries
    concatenate into one outsized batch — the shape that slams the
    scatter plane and the per-window accumulators at once."""

    name = "backpressure_wave"

    def __init__(self, seed: int = 0, compress: int = 2, jumbo: int = 4):
        super().__init__(seed)
        self.compress = max(1, int(compress))
        self.jumbo = max(1, int(jumbo))

    def apply(self, sim: Simulator, traffic: Traffic) -> Traffic:
        k = self.compress
        t_base = int(traffic.deliveries[0].t0) if traffic.deliveries else 0
        w0 = t_base // _WINDOW_NS
        for d in traffic.deliveries:
            ts = d.batch["write_time_ns"].astype(np.int64)
            w = ts // _WINDOW_NS - w0
            burst_w = np.maximum(w, 0) // k * k
            d.batch["write_time_ns"] = (
                (w0 + burst_w) * _WINDOW_NS + ts % _WINDOW_NS
            ).astype(np.uint64)
        merged: List[Delivery] = []
        for lo in range(0, len(traffic.deliveries), self.jumbo):
            group = traffic.deliveries[lo : lo + self.jumbo]
            pre = [p for d in group for p in d.pre]
            merged.append(
                Delivery(np.concatenate([d.batch for d in group]), pre=pre)
            )
        traffic.deliveries = merged
        traffic.meta["backpressure_wave"] = {
            "compress": k,
            "jumbo": self.jumbo,
            "deliveries": len(merged),
        }
        return traffic


def label_extra(batch, pairs: Set[Tuple[int, int]], span_ms: Tuple[int, int]) -> np.ndarray:
    """Oracle mask for incident-labeled pairs (the retry-storm victim
    edges): 1.0 where the batch edge's (src_uid, dst_uid) is in
    ``pairs`` and the window overlaps ``span_ms`` — composed with the
    fault plan's labels by max()."""
    labels = np.zeros(batch.e_pad, dtype=np.float32)
    if (
        batch.node_uids is None
        or not pairs
        or not (span_ms[0] <= batch.window_start_ms < span_ms[1])
    ):
        return labels
    keys = np.array(
        [(int(f) << 32) | int(t) for f, t in pairs], dtype=np.int64
    )
    uids = batch.node_uids
    edge_keys = (
        uids[batch.edge_src].astype(np.int64) << 32
    ) | uids[batch.edge_dst].astype(np.int64)
    hit = np.isin(edge_keys, keys)
    hit[batch.n_edges :] = False
    labels[hit] = 1.0
    return labels


# ---------------------------------------------------------------------------
# Scenario registry: name → incident factory per scale. "gate" is the
# fixed-seed acceptance scale (fast enough for tier-1 and the bench
# ride-along); "stress" is the acceptance BOUND scale (hot_key at 500k
# fan-in — bench --scenario / make scenarios --stress territory).
# ---------------------------------------------------------------------------

_GATE_SIM = dict(
    pod_count=40, service_count=10, edge_count=80, edge_rate=100,
    test_duration_s=6.0, chunk_size=4096,
)


def make_incident(name: str, seed: int = 0, scale: str = "gate") -> Incident:
    stress = scale == "stress"
    if name == "hot_key":
        return HotKey(
            seed,
            fan_in=500_000 if stress else 6_000,
            hot_windows=(2, 3),
        )
    if name == "deploy_rollout":
        return DeployRollout(seed, churn_frac=0.5, at_window=2)
    if name == "dns_storm":
        return DnsStorm(
            seed,
            n_names=20_000 if stress else 2_000,
            rows_per_window=40_000 if stress else 6_000,
        )
    if name == "retry_storm":
        return RetryStorm(seed, amp=6 if stress else 4)
    if name == "backpressure_wave":
        return BackpressureWave(seed, compress=2, jumbo=4)
    raise ValueError(f"unknown scenario {name!r}; pick one of {SCENARIO_NAMES}")


def scenario_degree_cap(name: str, scale: str = "gate") -> int:
    """The degree cap a scenario's host leg runs under: hot_key NEEDS
    one (that is the defense under test); the rest run capped too at a
    bound far above their honest fan-in, proving the cap is a no-op on
    non-pathological shapes."""
    if name == "hot_key":
        return 1_024 if scale == "stress" else 256
    return 4_096


# ---------------------------------------------------------------------------
# Host-plane leg: the REAL sharded pipeline under the scenario's traffic.
# ---------------------------------------------------------------------------


class _BuildTimer:
    """Per-window close instrumentation: wraps a GraphBuilder instance's
    build/build_from_partials and records (input rows, seconds) per
    call — the p99-close-latency / close-throughput gauges the eval
    record publishes. Runner-side only; production code is untouched."""

    def __init__(self, builder):
        self.records: List[Tuple[int, float]] = []
        self._build, self._bfp = builder.build, builder.build_from_partials

        def build(rows, *a, **k):
            t0 = time.perf_counter()
            out = self._build(rows, *a, **k)
            self.records.append((int(rows.shape[0]), time.perf_counter() - t0))
            return out

        def build_from_partials(parts, *a, **k):
            t0 = time.perf_counter()
            out = self._bfp(parts, *a, **k)
            self.records.append(
                (sum(int(p.rows) for p in parts), time.perf_counter() - t0)
            )
            return out

        builder.build = build
        builder.build_from_partials = build_from_partials

    def p99_s(self) -> float:
        if not self.records:
            return 0.0
        return float(np.percentile([s for _, s in self.records], 99))

    def min_close_rows_per_s(self, min_rows: int = 256) -> float:
        """Worst per-window close throughput over windows with at least
        ``min_rows`` input rows (tiny windows are all fixed overhead)."""
        rates = [r / s for r, s in self.records if r >= min_rows and s > 0]
        return float(min(rates)) if rates else float("inf")


def run_host_leg(
    name: str,
    seed: int = 0,
    scale: str = "gate",
    n_workers: int = 2,
    degree_cap: Optional[int] = None,
    chaos: Optional[ChaosConfig] = None,
    sim_cfg: Optional[SimulationConfig] = None,
    incident: Optional[Incident] = None,
    flush_timeout_s: float = 60.0,
    findings: Optional[List[str]] = None,
) -> dict:
    """Drive the scenario's traffic through the REAL sharded pipeline
    and gate the host-plane invariants: bounded flush/drain, exact
    ledger conservation (``sampled`` included), strictly-ascending
    exactly-once windows, and — when a cap is armed — per-dst fan-in
    bounded in every emitted batch. ``chaos`` arms the PR 6 seams on
    top (delivery perturbation + worker crashes): "hot-key during a
    degraded delivery" is this call with both args."""
    from alaz_tpu.aggregator.cluster import ClusterInfo
    from alaz_tpu.aggregator.sharded import ShardedIngest
    from alaz_tpu.events.intern import Interner
    from alaz_tpu.utils.ledger import DropLedger

    if findings is None:
        findings = []
    cap = scenario_degree_cap(name, scale) if degree_cap is None else int(degree_cap)
    interner = Interner()
    cfg = sim_cfg if sim_cfg is not None else SimulationConfig(seed=seed, **_GATE_SIM)
    sim = Simulator(cfg, interner=interner)
    kube = sim.setup()
    traffic = base_traffic(sim)
    inc = incident if incident is not None else make_incident(name, seed, scale)
    traffic = inc.apply(sim, traffic)

    cluster = ClusterInfo(interner)
    for m in kube:
        cluster.handle_msg(m)
    ledger = DropLedger()
    closed: List = []
    fault_hook = None
    bchaos = None
    if chaos is not None and chaos.enabled:
        from alaz_tpu.chaos.injectors import BatchChaos, WorkerChaos

        fault_hook = WorkerChaos(
            seed=chaos.seed,
            crash_prob=chaos.worker_crash_prob,
            stall_prob=chaos.worker_stall_prob,
            stall_s=chaos.worker_stall_s,
            max_crashes=chaos.worker_max_crashes,
            ensure_crash=True,
        )
        bchaos = BatchChaos(
            seed=chaos.seed + 1,
            dup_prob=chaos.batch_dup_prob,
            reorder_prob=chaos.batch_reorder_prob,
            late_prob=chaos.batch_late_prob,
            min_each=True,
        )
    pipe = ShardedIngest(
        n_workers,
        interner=interner,
        cluster=cluster,
        window_s=1.0,
        on_batch=closed.append,
        ledger=ledger,
        degree_cap=cap,
        sample_seed=seed,
        fault_hook=fault_hook,
        shed_block_s=2.0,
    )
    timer = _BuildTimer(pipe.builder)
    deliveries, late = traffic.deliveries, []
    if bchaos is not None:
        deliveries, late = bchaos.perturb(deliveries)
    end_ns = 0
    t0 = time.perf_counter()
    try:
        pipe.process_tcp(traffic.tcp)
        for d in deliveries:
            end_ns = max(end_ns, replay_delivery(pipe, d))
        # drain the 2-rung retry ladder before sealing (run_replay's rule)
        for _ in range(3):
            pipe.flush_retries(end_ns + 10_000_000_000)
            if pipe.drain(timeout_s=10.0) and pipe.pending_retries == 0:
                break
        tf = time.perf_counter()
        if not pipe.flush(timeout_s=flush_timeout_s):
            findings.append(f"{name}: flush did not complete in {flush_timeout_s}s")
        flush_wall = time.perf_counter() - tf
        for d in late:  # held-back deliveries land past the sealed horizon
            replay_delivery(pipe, d, now_ns=end_ns)
        if late and not pipe.flush(timeout_s=flush_timeout_s):
            findings.append(f"{name}: post-late flush did not complete")
        if not pipe.drain(timeout_s=15.0):
            findings.append(f"{name}: drain did not settle in 15s")
        if pipe.pending_retries:
            findings.append(
                f"{name}: {pipe.pending_retries} rows stuck in the retry queue"
            )
        wall = time.perf_counter() - t0
    finally:
        pipe.stop()

    from alaz_tpu.chaos.harness import emitted_rows

    delivered = sum(len(d) for d in deliveries) + sum(len(d) for d in late)
    emitted = emitted_rows(closed)
    stats = pipe.stats.as_dict()
    # semantic drops ride the ledger's `filtered` cause now (ISSUE 8) —
    # the gate is exactly delivered == emitted + ledger.total, no second
    # bookkeeper. The stats counters stay as the per-reason breakdown
    # and the cross-check below pins them to the ledgered total.
    semantic = (
        stats["l7_dropped_no_socket"]
        + stats["l7_dropped_not_pod"]
        + stats["l7_rate_limited"]
    )
    gap = ledger.conservation_gap(delivered, emitted)
    if gap != 0:
        findings.append(
            f"{name}: row conservation broken — delivered={delivered} "
            f"emitted={emitted} semantic={semantic} "
            f"ledger={ledger.snapshot()} gap={gap}"
        )
    if ledger.count("filtered") != semantic:
        findings.append(
            f"{name}: filtered-ledger drift — stats say {semantic} "
            f"semantic drops, ledger says {ledger.count('filtered')}"
        )
    starts = [b.window_start_ms for b in closed]
    if any(b <= a for a, b in zip(starts, starts[1:])):
        findings.append(
            f"{name}: window emission not strictly ascending: {starts}"
        )
    max_indeg = 0
    for b in closed:
        if b.n_edges:
            deg = np.bincount(b.edge_dst[: b.n_edges])
            max_indeg = max(max_indeg, int(deg.max()))
    if cap and max_indeg > cap:
        findings.append(
            f"{name}: emitted in-degree {max_indeg} exceeds degree_cap {cap}"
        )
    hk = traffic.meta.get("hot_key")
    if hk is not None and cap and hk["fan_in"] > cap and ledger.count("sampled") == 0:
        findings.append(
            f"{name}: fan-in {hk['fan_in']} over cap {cap} but nothing "
            "ledgered as sampled (the defense never fired)"
        )
    # the "no wall-clock blowup" bound: with the cap armed, no single
    # window close may stall a wave — 5s is an order of magnitude above
    # the measured 500k-fan-in close (~0.6s) and two below an uncapped
    # hot window's downstream cost, so it trips on a real stall, not on
    # a slow CI box
    p99 = timer.p99_s()
    if cap and p99 > 5.0:
        findings.append(
            f"{name}: p99 window close took {p99:.2f}s with the cap armed "
            "(close wave stalling)"
        )
    score_plane = run_drift_leg(
        name, closed, findings=findings, gated=chaos is None, interner=interner
    )
    return {
        "score_plane": score_plane,
        "scenario": name,
        "seed": seed,
        "scale": scale,
        "degree_cap": cap,
        "delivered_rows": int(delivered),
        "emitted_rows": int(emitted),
        "semantic_drops": int(semantic),
        "windows": len(closed),
        "max_emitted_indegree": max_indeg,
        "rows_per_sec": round(delivered / wall) if wall > 0 else 0,
        "flush_wall_s": round(flush_wall, 3),
        "close_p99_s": round(timer.p99_s(), 4),
        "min_close_rows_per_s": round(timer.min_close_rows_per_s()),
        "ledger": ledger.snapshot(),
        "meta": traffic.meta,
        "chaos": None
        if bchaos is None
        else {
            "duplicated": bchaos.duplicated,
            "reordered": bchaos.reordered,
            "late": bchaos.delayed,
            "crashes": fault_hook.crashes,
            "worker_restarts": pipe.worker_restarts,
        },
    }


# ---------------------------------------------------------------------------
# Score-plane drift leg (ISSUE 13): the emitted windows through the
# drift monitor, with the deterministic feature-space scorer.
# ---------------------------------------------------------------------------

# scenarios whose score distribution MUST trip a drift event on clean
# fixed seeds (the shapes the monitor exists for: an error cascade and
# a composition-shifting fan-in); dns_storm in practice trips too but
# is reported, not gated — its drift is a side effect, not the point
DRIFT_TRIP_SCENARIOS = ("retry_storm", "hot_key")
# a drift event later than this many windows after the incident's first
# hot window is a detection failure, not a page (the gate's N)
DRIFT_MAX_LAG_WINDOWS = 2


def run_drift_leg(
    name: str,
    closed: List,
    findings: Optional[List[str]] = None,
    gated: bool = True,
    interner=None,
) -> dict:
    """Feed the host leg's emitted windows (emission order) through a
    :class:`~alaz_tpu.obs.scores.ScorePlane` driven by the deterministic
    feature-space scorer, and gate the drift contract:

    - ``retry_storm`` / ``hot_key`` must raise a drift event within
      ``DRIFT_MAX_LAG_WINDOWS`` of the incident's first hot window;
    - ``deploy_rollout`` must REBASELINE (node-churn detection) without
      a drift false alarm;
    - anything else is report-only (dns_storm legitimately drifts).

    ``gated=False`` (chaos-perturbed runs) records but never gates —
    duplicated/late delivery legitimately reshapes per-window
    distributions. On a gate failure the top-K attribution ledger of
    the newest windows is attached to the finding, the trail an
    operator would pull from ``/scores/top``."""
    from alaz_tpu.obs.scores import ScorePlane, feature_scores

    if findings is None:
        findings = []
    plane = ScorePlane(
        enabled=True,
        model=name,
        # short fixed-seed runs (3-6 windows; a composed
        # backpressure_wave compresses to 3): a 2-window trailing
        # reference armed from the FIRST window, flip on the first
        # over-threshold compare — the production default (8, hysteresis
        # 2) would spend the whole run warming up
        drift_windows=2,
        min_ref=1,
        hysteresis=1,
        top_k=5,
        resolve=interner.lookup if interner is not None else None,
    )
    first_drift_window = None
    for i, b in enumerate(closed):
        plane.observe_window(b, feature_scores(b))
        if first_drift_window is None and plane.drift_events > 0:
            first_drift_window = i
    snap = plane.snapshot()
    out = {
        "windows": snap["windows"],
        "drift_events": snap["drift"]["events"],
        "rebaselines": snap["drift"]["rebaselines"],
        "first_drift_window": first_drift_window,
        "psi": snap["drift"]["psi"],
        "dist": snap["dist"],
    }
    if not gated:
        return out
    if name in DRIFT_TRIP_SCENARIOS:
        if plane.drift_events == 0:
            findings.append(
                f"{name}: score distribution never tripped the drift "
                f"monitor (psi last={snap['drift']['psi']}) — the plane "
                "missed the incident it exists for; top ledger: "
                f"{plane.top_snapshot(2)}"
            )
        else:
            # "within N windows": the incident's first hot window is 2
            # for every gated scenario (make_incident), and the drift
            # compare arms at window 2 — a first event past
            # 2 + DRIFT_MAX_LAG_WINDOWS means the monitor needed the
            # incident to persist unreasonably long before paging
            if first_drift_window > 2 + DRIFT_MAX_LAG_WINDOWS:
                findings.append(
                    f"{name}: drift event arrived at window "
                    f"{first_drift_window}, more than "
                    f"{DRIFT_MAX_LAG_WINDOWS} windows after the "
                    "incident onset"
                )
    if name == "deploy_rollout":
        if plane.rebaselines == 0:
            findings.append(
                f"{name}: node-table churn never rebaselined the drift "
                "reference — a real rollout would page as drift"
            )
        if plane.drift_events > 0:
            findings.append(
                f"{name}: drift false alarm across a rebaselining "
                f"rollout (events={plane.drift_events}); top ledger: "
                f"{plane.top_snapshot(2)}"
            )
    return out


# ---------------------------------------------------------------------------
# Detection leg: scenario-shaped traffic through the anomaly pipeline.
# ---------------------------------------------------------------------------

CLEAN_AUROC_GATE = 0.9  # test_train.py's clean gate
SCENARIO_AUROC_TOLERANCE = 0.05


def run_detection_leg(
    name: str,
    seed: int = 0,
    chaos=None,
    degree_cap: int = 0,
    findings: Optional[List[str]] = None,
) -> dict:
    """Train + evaluate the standard anomaly scenario over
    scenario-shaped traffic (incident-transformed simulator stream,
    optionally chaos-degraded delivery): blended AUROC must stay within
    ``SCENARIO_AUROC_TOLERANCE`` of the clean gate. Imports jax/train
    lazily — the host leg stays importable on data-plane images."""
    from alaz_tpu.config import ModelConfig
    from alaz_tpu.replay.scenario import run_anomaly_scenario
    from alaz_tpu.train import train_on_batches
    from alaz_tpu.train.metrics import auroc
    from alaz_tpu.train.trainstep import make_score_fn, score_batch

    if findings is None:
        findings = []
    sim_cfg = SimulationConfig(
        pod_count=50, service_count=20, edge_count=40, edge_rate=200, seed=seed
    )
    incident = make_detection_incident(name, seed)
    data = run_anomaly_scenario(
        sim_cfg,
        n_windows=8,
        fault_fraction=0.2,
        seed=seed + 1,
        chaos=chaos,
        incident=incident,
        degree_cap=degree_cap,
    )
    cfg = ModelConfig(model="graphsage", hidden_dim=64, use_pallas=False)
    state, losses = train_on_batches(cfg, data.train, epochs=25, lr=3e-3)
    fn = make_score_fn(cfg)
    scores, labels, masks = [], [], []
    for b in data.eval:
        out = score_batch(cfg, state.params, b, fn)
        scores.append(out["edge_logits"])
        labels.append(b.edge_label)
        masks.append(b.edge_mask)
    a = float(
        auroc(np.concatenate(scores), np.concatenate(labels), np.concatenate(masks))
    )
    floor = CLEAN_AUROC_GATE - SCENARIO_AUROC_TOLERANCE
    if a < floor:
        findings.append(
            f"{name}: blended AUROC {a:.3f} under the scenario fell past "
            f"the {floor:.2f} tolerance gate"
        )
    return {
        "scenario": name,
        "auroc": round(a, 4),
        "gate": floor,
        "train_windows": len(data.train),
        "eval_windows": len(data.eval),
        "final_loss": round(float(losses[-1]), 4),
    }


def make_detection_incident(name: str, seed: int = 0) -> Incident:
    """Detection-scale incidents: sized to the 50-pod standard scenario
    so training stays CI-cheap while the shape stress is still real."""
    if name == "hot_key":
        return HotKey(seed, fan_in=600, hot_windows=(3, 4))
    if name == "deploy_rollout":
        return DeployRollout(seed, churn_frac=0.4, at_window=3)
    if name == "dns_storm":
        return DnsStorm(seed, n_names=400, rows_per_window=1_500)
    if name == "retry_storm":
        return RetryStorm(seed, amp=3, storm_windows=(3, 4, 5))
    if name == "backpressure_wave":
        return BackpressureWave(seed, compress=2, jumbo=3)
    raise ValueError(f"unknown scenario {name!r}")


# ---------------------------------------------------------------------------
# Eval records + suite driver.
# ---------------------------------------------------------------------------


@dataclass
class ScenarioReport:
    name: str
    seed: int
    findings: List[str] = field(default_factory=list)
    host: dict = field(default_factory=dict)
    detection: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "scenario": self.name,
            "seed": self.seed,
            "scenario_findings": len(self.findings),
            "findings": self.findings,
            "host": self.host,
            "detection": self.detection,
        }


def run_incident_scenario(
    name: str,
    seed: int = 0,
    n_workers: int = 2,
    scale: str = "gate",
    detection: bool = True,
    chaos: Optional[ChaosConfig] = None,
    degree_cap: Optional[int] = None,
    incident: Optional[Incident] = None,
) -> ScenarioReport:
    """One scenario's full eval record: host-plane gates (always) +
    the detection gate (skippable for ride-alongs — training is the
    expensive half). ``incident`` overrides the registry's default
    construction (how the suite drivers re-scale via ScenarioConfig)."""
    rep = ScenarioReport(name=name, seed=seed)
    rep.host = run_host_leg(
        name,
        seed=seed,
        scale=scale,
        n_workers=n_workers,
        degree_cap=degree_cap,
        chaos=chaos,
        incident=incident,
        findings=rep.findings,
    )
    if detection:
        from alaz_tpu.chaos.injectors import BatchChaos

        det_chaos = None
        if chaos is not None and chaos.enabled:
            det_chaos = BatchChaos(
                seed=chaos.seed + 7,
                dup_prob=chaos.batch_dup_prob,
                reorder_prob=chaos.batch_reorder_prob,
                late_prob=chaos.batch_late_prob,
                min_each=True,
            )
        # same cap resolution as the host leg: the published record
        # pairs (degree_cap, blended_auroc), so the AUROC must be
        # measured with the cap ARMED, not the uncapped default
        rep.detection = run_detection_leg(
            name,
            seed=seed,
            chaos=det_chaos,
            degree_cap=(
                degree_cap
                if degree_cap is not None
                else scenario_degree_cap(name, scale)
            ),
            findings=rep.findings,
        )
    for f in rep.findings:
        log.warning(f"scenario finding: {f}")
    return rep


def run_scenario_suite(
    seed: int = 0,
    names: Sequence[str] = SCENARIO_NAMES,
    n_workers: int = 2,
    detection: bool = False,
    scale: str = "gate",
) -> List[ScenarioReport]:
    """The fixed-seed sweep: every scenario's gates at ``scale``. With
    ``detection=False`` this is the fast host-plane pass `bench.py
    --ingest` rides along with (scenario_findings, expected 0)."""
    return [
        run_incident_scenario(
            n, seed=seed, n_workers=n_workers, scale=scale, detection=detection
        )
        for n in names
    ]
