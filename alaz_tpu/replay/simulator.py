"""Deterministic synthetic workload generator.

Mirrors the reference Simulator (main_benchmark_test.go:311-633): fabricate
``pod_count`` pods and ``service_count`` services, pick ``edge_count``
pod→service edges each with a unique (pid, fd) and a TCP-establish event,
then emit HTTP traffic at ``edge_rate`` events/s/edge for
``test_duration_s`` — except the traffic is generated as columnar batches
on a virtual clock, so replay runs as fast as the pipeline can go and
throughput is measured rather than imposed.

The acceptance invariant is the reference's own (main_benchmark_test.go:
140-147): ≥90% of ``duration × edges × rate`` events must come out of the
pipeline as persisted requests.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Iterator, List

import numpy as np

from alaz_tpu.aggregator.engine import Aggregator
from alaz_tpu.config import SimulationConfig
from alaz_tpu.datastore.inmem import InMemDataStore
from alaz_tpu.events.intern import Interner
from alaz_tpu.events.k8s import (
    EventType,
    K8sResourceMessage,
    Pod,
    ResourceType,
    Service,
)
from alaz_tpu.events.net import ip_to_u32, u32_to_ip
from alaz_tpu.events.schema import (
    HttpMethod,
    L7Protocol,
    TCP_EVENT_DTYPE,
    TcpEventType,
    make_l7_events,
    set_payloads,
)

_BASE_TIME_NS = 1_700_000_000_000_000_000

_PROTO_PAYLOADS = {
    "HTTP": (L7Protocol.HTTP, HttpMethod.GET, b"GET /user HTTP/1.1\r\nHost: svc\r\n\r\n"),
    "POSTGRES": (
        L7Protocol.POSTGRES,
        2,  # PostgresMethod.SIMPLE_QUERY
        b"Q\x00\x00\x00\x20SELECT id, name FROM users\x00",
    ),
    "REDIS": (L7Protocol.REDIS, 1, b"*2\r\n$3\r\nGET\r\n$7\r\nuser:42\r\n"),
    "MYSQL": (
        L7Protocol.MYSQL,
        1,
        b"\x1c\x00\x00\x00\x03SELECT id FROM users LIMIT 1",
    ),
}


@dataclass
class SimEdge:
    pod_idx: int
    svc_idx: int
    pid: int
    fd: int
    conn_ts: int
    protocol: str


class Simulator:
    def __init__(self, config: SimulationConfig, interner: Interner | None = None):
        self.cfg = config
        self.rng = np.random.default_rng(config.seed)
        self.interner = interner if interner is not None else Interner()
        self.pods: List[Pod] = []
        self.services: List[Service] = []
        self.edges: List[SimEdge] = []
        self._setup_done = False  # lockless-ok: setup() completes before any delivery thread reads it (bool flip is a single store; asserts are the only readers)

    # -- topology ----------------------------------------------------------

    def setup(self) -> List[K8sResourceMessage]:
        """Create pods/services (PodCreateEvent/ServiceCreateEvent analog)
        and pick edges; returns the kube-event stream."""
        cfg = self.cfg
        msgs: List[K8sResourceMessage] = []
        for i in range(cfg.pod_count):
            ip = u32_to_ip(ip_to_u32("10.0.0.0") + 1 + i)
            pod = Pod(
                uid=f"pod-uid-{i}",
                name=f"pod-{i}",
                namespace="default",
                image=f"img-{i % 7}",
                ip=ip,
            )
            self.pods.append(pod)  # alazlint: disable=ALZ051 -- setup() completes before any delivery thread starts (the _setup_done contract); the topology lists are append-frozen thereafter
            msgs.append(K8sResourceMessage(ResourceType.POD, EventType.ADD, pod))
        for i in range(cfg.service_count):
            ip = u32_to_ip(ip_to_u32("10.96.0.0") + 1 + i)
            svc = Service(
                uid=f"svc-uid-{i}",
                name=f"svc-{i}",
                namespace="default",
                cluster_ip=ip,
                cluster_ips=[ip],
            )
            self.services.append(svc)  # alazlint: disable=ALZ051 -- setup() completes before any delivery thread starts (the _setup_done contract); the topology lists are append-frozen thereafter
            msgs.append(K8sResourceMessage(ResourceType.SERVICE, EventType.ADD, svc))

        protos = list(cfg.protocol_mix.keys())
        weights = np.asarray([cfg.protocol_mix[p] for p in protos], dtype=np.float64)
        weights = weights / weights.sum()
        pod_idx = self.rng.integers(0, cfg.pod_count, size=cfg.edge_count)
        svc_idx = self.rng.integers(0, cfg.service_count, size=cfg.edge_count)
        fds = self.rng.choice(np.arange(10, 10 + 10 * cfg.edge_count), size=cfg.edge_count, replace=False)
        pids = 1000 + pod_idx  # one pid per pod
        proto_pick = self.rng.choice(len(protos), size=cfg.edge_count, p=weights)
        for e in range(cfg.edge_count):
            self.edges.append(  # alazlint: disable=ALZ051 -- setup() completes before any delivery thread starts (the _setup_done contract); the topology lists are append-frozen thereafter
                SimEdge(
                    pod_idx=int(pod_idx[e]),
                    svc_idx=int(svc_idx[e]),
                    pid=int(pids[e]),
                    fd=int(fds[e]),
                    conn_ts=_BASE_TIME_NS + int(self.rng.integers(0, 1_000_000)),
                    protocol=protos[proto_pick[e]],
                )
            )
        self._setup_done = True
        return msgs

    def tcp_events(self) -> np.ndarray:
        """One ESTABLISHED per edge (tcpEstablish analog,
        main_benchmark_test.go:622-633)."""
        assert self._setup_done
        ev = np.zeros(len(self.edges), dtype=TCP_EVENT_DTYPE)
        for i, e in enumerate(self.edges):
            ev["pid"][i] = e.pid
            ev["fd"][i] = e.fd
            ev["timestamp_ns"][i] = e.conn_ts
            ev["type"][i] = TcpEventType.ESTABLISHED
            ev["saddr"][i] = ip_to_u32(self.pods[e.pod_idx].ip)
            ev["sport"][i] = 40_000 + i
            ev["daddr"][i] = ip_to_u32(self.services[e.svc_idx].cluster_ip)
            ev["dport"][i] = 80
        return ev

    @property
    def expected_events(self) -> int:
        return int(self.cfg.edge_count * self.cfg.edge_rate * self.cfg.test_duration_s)

    def iter_l7_batches(self) -> Iterator[np.ndarray]:
        """Time-ordered L7 event batches across all edges.

        Each edge contributes ``rate × duration`` events with evenly spread
        virtual write timestamps starting just after its TCP establish
        (WriteTimeNs = conn_ts + 10 in the reference's httpTraffic,
        main_benchmark_test.go:597)."""
        assert self._setup_done
        cfg = self.cfg
        per_edge = int(cfg.edge_rate * cfg.test_duration_s)
        n_edges = len(self.edges)
        total = per_edge * n_edges
        if total == 0:
            return

        # interleave edges round-robin so batches are time-sorted without a
        # global 3M-element sort: event k of edge e has ts = base + k*dt(+e)
        dt = int(1e9 / cfg.edge_rate)
        chunk = cfg.chunk_size
        # per-edge constant columns
        pid = np.array([e.pid for e in self.edges], dtype=np.uint32)
        fd = np.array([e.fd for e in self.edges], dtype=np.uint64)
        conn = np.array([e.conn_ts for e in self.edges], dtype=np.uint64)
        proto_rows = {}
        for name, (proto, method, payload) in _PROTO_PAYLOADS.items():
            proto_rows[name] = (proto, method, payload)
        edge_proto = np.array(
            [proto_rows[e.protocol][0] for e in self.edges], dtype=np.uint8
        )
        edge_method = np.array(
            [proto_rows[e.protocol][1] for e in self.edges], dtype=np.uint8
        )

        emitted = 0
        k = 0  # per-edge sequence number
        while emitted < total:
            rows_this = min(chunk, total - emitted)
            # how many full rounds of n_edges fit
            ev = make_l7_events(rows_this)
            idx = np.arange(rows_this)
            edge_ids = (k + idx) % n_edges
            seq = (k + idx) // n_edges
            ev["pid"] = pid[edge_ids]
            ev["fd"] = fd[edge_ids]
            ev["write_time_ns"] = conn[edge_ids] + 10 + seq.astype(np.uint64) * np.uint64(dt)
            ev["duration_ns"] = 50
            ev["protocol"] = edge_proto[edge_ids]
            ev["method"] = edge_method[edge_ids]
            ev["status"] = 200
            # payloads: group rows by edge protocol, one memcpy per protocol
            for name, (proto, method, payload) in _PROTO_PAYLOADS.items():
                mask = ev["protocol"] == proto
                if not mask.any():
                    continue
                if mask.all():
                    # single-protocol batch (config1 is HTTP-only): write
                    # payloads in place — fancy-indexed structured-array
                    # round-trips copy the whole batch twice — and stop
                    # scanning: no other protocol can match
                    set_payloads(ev, payload)
                    break
                else:
                    sub = ev[mask]
                    set_payloads(sub, payload)
                    ev[mask] = sub
            k += rows_this
            emitted += rows_this
            yield ev


@dataclass
class ReplayResult:
    generated: int
    persisted: int
    wall_s: float
    events_per_s: float
    processed_ratio: float
    aggregator_stats: dict = field(default_factory=dict)

    @property
    def passed(self) -> bool:
        """The reference's ≥90% acceptance (main_benchmark_test.go:140-147)."""
        return self.processed_ratio >= 0.9


def run_replay(
    config: SimulationConfig,
    ds: InMemDataStore | None = None,
    aggregator: Aggregator | None = None,
) -> ReplayResult:
    """Synchronous replay: simulator → aggregator → datastore, flat out."""
    interner = Interner()
    if ds is None:
        ds = InMemDataStore()
    if aggregator is None:
        aggregator = Aggregator(ds, interner=interner)
    sim = Simulator(config, interner=interner)

    t0 = time.perf_counter()
    for msg in sim.setup():
        aggregator.process_k8s(msg)
    aggregator.process_tcp(sim.tcp_events())
    generated = 0
    now_ns = _BASE_TIME_NS
    for batch in sim.iter_l7_batches():
        generated += batch.shape[0]
        now_ns = int(batch["write_time_ns"][-1])
        aggregator.process_l7(batch, now_ns=now_ns)
    # drain any retries
    for _ in range(RETRY_DRAIN_ROUNDS):
        if not aggregator._retries:
            break
        aggregator.flush_retries(now_ns + 10_000_000_000)
    wall = time.perf_counter() - t0

    persisted = ds.request_count
    return ReplayResult(
        generated=generated,
        persisted=persisted,
        wall_s=wall,
        events_per_s=generated / wall if wall > 0 else 0.0,
        processed_ratio=persisted / generated if generated else 0.0,
        aggregator_stats=aggregator.stats.as_dict(),
    )


RETRY_DRAIN_ROUNDS = 5
