"""CLI driver for the incident scenario suite — what ``make scenarios``
runs.

One JSON line per (scenario, seed); exit 1 if any produced findings.
The default sweep is the fixed-seed acceptance set: every scenario's
host-plane gates (bounded flush/drain, ledger conservation with the
``sampled`` cause, exactly-once windows, cap respected) plus — unless
``--no-detection`` — the per-scenario detection gate (blended AUROC
within tolerance of the clean gate). ``--stress`` additionally runs the
hot_key acceptance bound (500k fan-in, degree-capped) host leg.
"""

from __future__ import annotations

import argparse
import json
import sys

from alaz_tpu.config import ScenarioConfig
from alaz_tpu.replay.incidents import (
    SCENARIO_NAMES,
    HotKey,
    run_incident_scenario,
)


def main(argv=None) -> int:
    scfg = ScenarioConfig.from_env()
    p = argparse.ArgumentParser(
        prog="python -m alaz_tpu.replay",
        description="run the incident scenario suite (fixed seeds, all scenarios)",
    )
    p.add_argument("--seeds", type=int, nargs="+", default=[scfg.seed])
    p.add_argument(
        "--scenarios", nargs="+", default=list(SCENARIO_NAMES),
        choices=list(SCENARIO_NAMES),
    )
    p.add_argument("--workers", type=int, default=scfg.n_workers)
    p.add_argument(
        "--no-detection", action="store_true",
        help="host-plane gates only (skip the training leg)",
    )
    p.add_argument(
        "--stress", action="store_true",
        help="also run the hot_key 500k-fan-in acceptance bound (host leg)",
    )
    p.add_argument(
        "--isolation", action="store_true",
        help="also run the multi-tenant isolation gate (ISSUE 14): K=3 "
        "tenants on one service, one perturbed by an incident — clean "
        "tenants must hold latency vs solo, stay drift-silent, and "
        "conserve rows exactly per tenant",
    )
    p.add_argument(
        "--isolation-tenants", type=int, default=3,
        help="tenant count for the isolation gate",
    )
    args = p.parse_args(argv)

    failed = 0
    for seed in args.seeds:
        for name in args.scenarios:
            rep = run_incident_scenario(
                name,
                seed=seed,
                n_workers=args.workers,
                detection=not args.no_detection,
            )
            print(json.dumps(rep.as_dict(), sort_keys=True), flush=True)
            if not rep.ok:
                failed += 1
    if args.isolation:
        from alaz_tpu.replay.tenants import run_isolation_scenario

        trep = run_isolation_scenario(
            tenants=args.isolation_tenants, seed=args.seeds[0]
        )
        print(json.dumps(trep.as_dict(), sort_keys=True), flush=True)
        if not trep.ok:
            failed += 1
    if args.stress:
        rep = run_incident_scenario(
            "hot_key",
            seed=args.seeds[0],
            n_workers=args.workers,
            scale="stress",
            detection=False,
            degree_cap=scfg.degree_cap,
            incident=HotKey(args.seeds[0], fan_in=scfg.hot_key_fanin),
        )
        print(json.dumps(rep.as_dict(), sort_keys=True), flush=True)
        if not rep.ok:
            failed += 1
    if failed:
        print(f"# {failed} scenario run(s) with findings", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
