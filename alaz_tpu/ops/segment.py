"""Segment reductions over COO edges (XLA path).

These are the message-passing primitives every model in models/ is built
on: gather source-node rows, reduce them per destination node. The XLA
lowering of ``jax.ops.segment_sum`` is a sorted scatter-add; the Pallas
path (ops/pallas_segment.py) beats it by turning the scatter into MXU
one-hot matmuls over dst-sorted edge blocks. ``gather_scatter_sum``
dispatches between them.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from alaz_tpu.graph.snapshot import EDGE_BLOCK_ROWS

# Warn-once latches for the dispatch fallbacks below. The bare
# module-global check-then-act ("if not WARNED: WARNED = True; log")
# was the exact race shape PR 18 closed elsewhere: two threads hitting
# the first fallback concurrently both observe False and both log.
# The flip is double-checked under _WARN_LOCK; the log call itself runs
# OUTSIDE the lock (lock-order discipline — get_logger may take the
# logging module's own lock, and nothing else may nest under ours).
_WARN_LOCK = threading.Lock()
_FALLBACK_WARNED = False


def _warn_once_fallback() -> bool:
    """Atomically claim the pallas-fallback warning; True for the one
    caller that should emit it."""
    global _FALLBACK_WARNED
    with _WARN_LOCK:
        claimed = not _FALLBACK_WARNED
        _FALLBACK_WARNED = True
    return claimed


def segment_sum(data: jnp.ndarray, segment_ids: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def blocked_segment_sum(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    block_starts: jnp.ndarray,
    num_segments: int,
    out_dtype=None,
) -> jnp.ndarray:
    """[E, F] → [N, F] sum using the precomputed dst-block extents — the
    blocked layout's XLA fallback (ISSUE 20, ARCHITECTURE §3v).

    ``block_starts`` is the blocked-CSR row-start vector the host emits
    at window close (graph/snapshot.py ``edge_block_starts_from``):
    entry b is the first edge of dst block b, and ``block_starts[-1]``
    is the live-edge FRONTIER — every slot at or past it is bucket
    padding. The edge axis is viewed as 128-row tiles, slots past the
    frontier are zeroed (so pad slots contribute exactly 0.0 — the COO
    path's edge_mask discipline, enforced here by construction), and
    the result is the plain sorted segment reduce over the masked
    tiles. Bit-exact vs the COO path on every real node row: masking
    only ever ADDS exact zeros, and f32 addition of 0.0 is the
    identity. The CPU win comes from the caller dispatching at the
    TILE-TRIMMED shape (``ceil(n_edges/128)·128`` rows instead of the
    bucket rung — bench.py ``layout_ab`` measures it); inside a
    fixed-bucket jit the same code is the bit-exact parity surface the
    Pallas extent kernel is tested against."""
    e = data.shape[0]
    assert e % EDGE_BLOCK_ROWS == 0, f"edge axis {e} not tile-aligned"
    n_tiles = e // EDGE_BLOCK_ROWS
    pos = (
        jax.lax.broadcasted_iota(jnp.int32, (n_tiles, EDGE_BLOCK_ROWS), 0)
        * EDGE_BLOCK_ROWS
        + jax.lax.broadcasted_iota(jnp.int32, (n_tiles, EDGE_BLOCK_ROWS), 1)
    )
    live = (pos < block_starts[-1]).reshape(e)
    if data.ndim > 1:
        live = live.reshape((e,) + (1,) * (data.ndim - 1))
    masked = jnp.where(live, data, jnp.zeros((), dtype=data.dtype))
    out = jax.ops.segment_sum(masked, segment_ids, num_segments=num_segments)
    return out if out_dtype is None else out.astype(out_dtype)


def segment_mean(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    weights: jnp.ndarray | None = None,
    block_starts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Mean with masked counts: ``weights`` (0/1 per edge) excludes padding
    edges from both numerator and denominator. ``block_starts`` routes
    the three reductions through the blocked fallback (frontier-masked
    tiles) — bit-exact, since pad edges carry weight 0 either way."""
    if block_starts is not None:
        def _sum(d):
            return blocked_segment_sum(d, segment_ids, block_starts, num_segments)
    else:
        def _sum(d):
            return jax.ops.segment_sum(d, segment_ids, num_segments=num_segments)

    if weights is not None:
        data = data * weights[:, None]
        counts = _sum(weights)
    else:
        counts = _sum(jnp.ones(segment_ids.shape[0], dtype=data.dtype))
    totals = _sum(data)
    return totals / jnp.maximum(counts, 1.0)[:, None]


def segment_max(data: jnp.ndarray, segment_ids: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


def pallas_enabled(use_pallas: bool | str) -> bool:
    """THE predicate for Pallas sorted-kernel dispatch: on when requested
    and the backend is TPU, or when forced off-TPU with the string
    ``"interpret"`` (pl.pallas_call interpret mode — how the sharding
    tests exercise kernel+shard_map on a CPU mesh). One definition so a
    new mode string cannot diverge between the dispatch sites."""
    return (bool(use_pallas) and jax.default_backend() == "tpu") or use_pallas == "interpret"


def expand_dst(
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    use_pallas: bool | str = False,
) -> jnp.ndarray:
    """[N, F] → [E, F] broadcast ``v[segment_ids]`` for dst-SORTED ids.

    The single dispatch point for the sorted-expand Pallas kernel (an XLA
    row gather is row-op bound, ~9 ns/row on TPU *in isolation*): kernel
    on TPU, interpret mode when forced with ``"interpret"``, XLA gather
    elsewhere.

    ``ALAZ_EXPAND_DST=xla|pallas`` overrides the dispatch: the r03 trace
    (ARCHITECTURE §3d) shows the in-graph XLA gather at F=128 costs
    1.9 ms vs the kernel's 2.4 ms — XLA pipelines row descriptors across
    the step far better than the isolated microbenchmark suggested — so
    the next capture A/Bs this knob before any default flips."""
    import os

    forced = os.environ.get("ALAZ_EXPAND_DST", "")
    if forced not in ("", "xla", "pallas"):
        # a typo'd A/B run must not silently measure the default path
        # under the override's label
        raise ValueError(
            f"ALAZ_EXPAND_DST={forced!r}: must be 'xla' or 'pallas'"
        )
    if forced == "xla":
        return v[segment_ids]
    if (forced == "pallas") or pallas_enabled(use_pallas):
        from alaz_tpu.ops.pallas_segment import segment_expand_sorted

        return segment_expand_sorted(v, segment_ids, num_segments)
    return v[segment_ids]


def segment_sum_sorted_dispatch(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    use_pallas: bool | str = False,
    out_dtype=None,
    block_starts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """[E, F] → [N, F] sum over dst-SORTED segment ids, dispatched like
    ``expand_dst``: Pallas one-hot scatter on TPU (DMA-bound, ~2× the
    XLA scatter's row-op-bound rate — ARCHITECTURE.md §3b table),
    interpret mode when forced, XLA ``segment_sum`` elsewhere.
    ``out_dtype`` requests the kernel path emit that dtype straight from
    its f32 accumulator (no input-dtype rounding); the XLA path casts.
    ``block_starts`` (the blocked layout's precomputed extents) hands
    the kernel its per-block row starts — no on-device binary search —
    and routes the fallback through ``blocked_segment_sum``."""
    if pallas_enabled(use_pallas):
        from alaz_tpu.ops.pallas_segment import scatter_sum_sorted

        return scatter_sum_sorted(
            data, segment_ids, num_segments, out_dtype, block_starts
        )
    if block_starts is not None:
        return blocked_segment_sum(
            data, segment_ids, block_starts, num_segments, out_dtype
        )
    out = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    return out if out_dtype is None else out.astype(out_dtype)


# THE attention-logit clamp for the fused softmax-aggregate (models/gat.py
# layer_fn and parallel/halo.py ring_attention_aggregate share it):
# softmax(clip(x)) == softmax(x) whenever |x| <= the clamp, and exp(30)
# ~ 1e13 keeps f32 segment sums far from overflow at million-edge fan-in.
# One definition so the single-device and ring implementations of the
# same math cannot drift.
ATTENTION_LOGIT_CLAMP = 30.0


def segment_sum_accurate(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    use_pallas: bool | str = False,
    block_starts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """``segment_sum_sorted_dispatch`` with guaranteed f32 ACCUMULATION
    and a LOSSLESS f32 result. The Pallas kernel accumulates f32 on the
    MXU whatever the input dtype (bf16 input just halves the DMA bytes)
    and ``out_dtype=f32`` makes it emit the accumulator directly — no
    input-dtype rounding on the way out. XLA's segment_sum accumulates
    AT the input dtype, and a bf16 running sum stagnates once increments
    fall below 2^-8 of the partial (fan-in ~256: 2048 bf16 ones sum to
    256) — so the fallback path upcasts first. Use this wherever the sum
    feeds a normalization (softmax denominators); plain feature scatters
    can tolerate the cheaper dispatch."""
    if not pallas_enabled(use_pallas):
        data = data.astype(jnp.float32)
    return segment_sum_sorted_dispatch(
        data, segment_ids, num_segments, use_pallas,
        out_dtype=jnp.float32, block_starts=block_starts,
    )


_SRC_GATHER_MODES = ("xla", "banded", "banded-interpret")
# same double-checked latch discipline as _FALLBACK_WARNED (top of file)
_banded_fallback_warned = False


def _warn_once_banded() -> bool:
    """Atomically claim the banded-off-TPU warning; True for the one
    caller that should emit it."""
    global _banded_fallback_warned
    with _WARN_LOCK:
        claimed = not _banded_fallback_warned
        _banded_fallback_warned = True
    return claimed


def gather_src(
    v: jnp.ndarray,
    src_ids: jnp.ndarray,
    num_nodes: int,
    mode: str = "xla",
) -> jnp.ndarray:
    """[N, F] → [E, F] gather ``v[src_ids]`` for UNSORTED src ids — the
    §3b residual. ``mode``: "xla" (row gather; right for uniform-random
    layouts), "banded" (Pallas windowed kernel on TPU; right after the
    cluster_renumber layout pass narrows per-chunk id bands), or
    "banded-interpret" to force the kernel off-TPU for tests. An unknown
    mode raises — a typo silently measuring the wrong path would poison
    every '[banded]'-tagged benchmark row."""
    import jax

    if mode not in _SRC_GATHER_MODES:
        raise ValueError(
            f"src_gather mode {mode!r}; expected one of {_SRC_GATHER_MODES}"
        )
    if (mode == "banded" and jax.default_backend() == "tpu") or mode == "banded-interpret":
        from alaz_tpu.ops.pallas_segment import gather_rows_banded

        return gather_rows_banded(v, src_ids, num_nodes)
    if mode == "banded":
        if _warn_once_banded():
            from alaz_tpu.logging import get_logger

            get_logger("alaz_tpu.ops").warning(
                "src_gather=banded requested off-TPU; using the XLA gather"
            )
    return v[src_ids]


def segment_softmax(
    logits: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: jnp.ndarray | None = None,
    use_pallas: bool | str = False,
) -> jnp.ndarray:
    """Per-segment softmax over edge logits (GAT attention normalization).

    ``logits`` may be [E] or [E, H] (all heads in one call — one batched
    segment op instead of a vmap of H row ops). Masked edges get -inf
    logits so they contribute zero weight. With ``use_pallas`` and
    dst-sorted segment ids, the two per-edge normalizer broadcasts ride
    the sorted-expand kernel instead of row-op-bound XLA gathers."""
    squeeze = logits.ndim == 1
    if squeeze:
        logits = logits[:, None]
    if mask is not None:
        logits = jnp.where(mask[:, None], logits, -1e30)
    seg_max = jax.ops.segment_max(logits, segment_ids, num_segments=num_segments)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    exp = jnp.exp(logits - expand_dst(seg_max, segment_ids, num_segments, use_pallas))
    if mask is not None:
        exp = jnp.where(mask[:, None], exp, 0.0)
    denom = segment_sum_sorted_dispatch(
        exp, segment_ids, num_segments, use_pallas
    )
    denom_e = expand_dst(denom, segment_ids, num_segments, use_pallas)
    # double-where guard: an all-masked segment (the pad tail) has
    # denom 0, and a bare eps-clamped division NaNs in the BACKWARD
    # (d(x/y)/dy = -x/y² with y²=1e-60 → f32 underflow → 0/0). XLA's
    # gather-VJP confines that NaN to the masked pad row, but the
    # one-hot-matmul kernel VJPs spread any NaN row across the whole
    # chunk (0·NaN=NaN in the MXU sum) — so make the division itself
    # safe instead of relying on masking downstream.
    nonempty = denom_e > 0.0
    out = jnp.where(
        nonempty, exp / jnp.where(nonempty, denom_e, 1.0), 0.0
    )
    return out[:, 0] if squeeze else out


def gather_scatter_sum(
    x: jnp.ndarray,
    edge_src: jnp.ndarray,
    edge_dst: jnp.ndarray,
    num_nodes: int,
    edge_weight: jnp.ndarray | None = None,
    use_pallas: bool | None = None,
    block_starts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """out[d] = Σ_{e: dst[e]=d} w[e] · x[src[e]] — the GNN hot loop.

    Dispatches to the Pallas TPU kernel when edges are dst-sorted (the
    GraphBatch layout guarantees this) and a TPU backend is active;
    otherwise the XLA gather + segment_sum path. ``block_starts`` (the
    blocked layout's precomputed extents) routes the scatter half
    through ``blocked_segment_sum``; the Pallas kernel path ignores it
    here because ``pallas_gather_scatter_sum`` fuses gather+scatter in
    one grid and carries its own row-start prefetch.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        try:
            from alaz_tpu.ops.pallas_segment import pallas_gather_scatter_sum

            return pallas_gather_scatter_sum(x, edge_src, edge_dst, num_nodes, edge_weight)
        except Exception as exc:  # pragma: no cover - lowering issues
            if _warn_once_fallback():
                from alaz_tpu.logging import get_logger

                get_logger("alaz_tpu.ops").warning(
                    f"pallas scatter unavailable, falling back to XLA "
                    f"(throughput regression!): {type(exc).__name__}: {exc}"
                )
    msgs = x[edge_src]
    if edge_weight is not None:
        msgs = msgs * edge_weight[:, None]
    if block_starts is not None:
        return blocked_segment_sum(msgs, edge_dst, block_starts, num_nodes)
    return jax.ops.segment_sum(msgs, edge_dst, num_segments=num_nodes)
