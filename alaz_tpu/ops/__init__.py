"""Sparse message-passing primitives for TPU.

``segment``: XLA-lowered gather/segment reductions (work everywhere).
``pallas_segment``: the hot-path Pallas kernel — edges sorted by
destination, scatter-add realized as per-block one-hot matmuls on the MXU
(the standard dense-hardware trick for sparse aggregation; cf. PAPERS.md
"Fast Training of Sparse GNNs on Dense Hardware").
"""

from alaz_tpu.ops.segment import gather_scatter_sum, segment_mean, segment_softmax, segment_sum

__all__ = [
    "gather_scatter_sum",
    "segment_mean",
    "segment_softmax",
    "segment_sum",
]
