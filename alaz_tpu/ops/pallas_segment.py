"""Pallas TPU kernel for the GNN hot loop: dst-sorted scatter-add as MXU
one-hot matmuls.

``out[d] = Σ_{e: dst[e]=d} msgs[e]`` with edges sorted by destination (the
GraphBatch layout). Instead of a serialized scatter-add, each 128-row
destination block computes ``onehotᵀ @ msg_chunk`` on the MXU over exactly
the edge chunks that intersect its range (binary-searched boundaries are
scalar-prefetched), with double-buffered DMA from HBM. This is the
"sparse graph ops on dense hardware" formulation (PAPERS.md) — the FLOPs
are redundant but land on the 128×128 systolic array, which beats
bandwidth-bound scatter on TPU.

The op is linear, so the backward pass is the same gather/scatter with
src/dst exchanged — expressed via the XLA path (edges aren't src-sorted).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from alaz_tpu.ops.constants import (  # shared with host cost models
    BAND_WINDOWS,
    TILE_E,
)

TILE_N = 128  # destination rows per grid step (= MXU width)
_DST_ROWS = TILE_E // 128  # 128-edge sub-rows per chunk


def _scatter_kernel(row_start_ref, msgs_hbm, dst_hbm, out_ref, msg_scratch, dst_scratch, sems):
    i = pl.program_id(0)
    e_lo = row_start_ref[i]
    e_hi = row_start_ref[i + 1]
    c0 = e_lo // TILE_E
    c1 = pl.cdiv(e_hi, TILE_E)

    out_ref[:] = jnp.zeros_like(out_ref)

    def make_dmas(slot, c):
        # One big msgs DMA per chunk; dst ids as _DST_ROWS separate
        # [1,128] row DMAs (int32 HBM slices tile at (8,128): only
        # single-row 128-wide slices lower — wider single rows hit the
        # same dim-0 alignment rejection). TILE_E=512 amortizes the
        # DMA-issue cost the kernel is actually bound by.
        dmas = [
            pltpu.make_async_copy(
                msgs_hbm.at[pl.ds(c * TILE_E, TILE_E), :],
                msg_scratch.at[slot],
                sems.at[slot, 0],
            )
        ]
        for r in range(_DST_ROWS):
            dmas.append(
                pltpu.make_async_copy(
                    dst_hbm.at[pl.ds(c * _DST_ROWS + r, 1), :],
                    dst_scratch.at[slot, pl.ds(r, 1)],
                    sems.at[slot, 1 + r],
                )
            )
        return dmas

    @pl.when(c1 > c0)
    def _():
        for dma in make_dmas(0, c0):
            dma.start()

        def body(c, _):
            slot = jax.lax.rem(c - c0, 2)
            next_slot = 1 - slot

            @pl.when(c + 1 < c1)
            def _():
                for dma in make_dmas(next_slot, c + 1):
                    dma.start()

            for dma in make_dmas(slot, c):
                dma.wait()

            # edges whose dst falls outside this block one-hot to zero rows,
            # so chunk overlap with neighboring blocks needs no masking.
            # One 128-edge sub-row at a time (Mosaic can't reshape the
            # (r,128) id block to (TILE_E,1) in one go).
            acc = jnp.zeros_like(out_ref)
            for r in range(_DST_ROWS):
                dst_local = dst_scratch[slot, r, :].reshape(128, 1) - i * TILE_N
                onehot = (
                    dst_local
                    == jax.lax.broadcasted_iota(jnp.int32, (128, TILE_N), 1)
                ).astype(msg_scratch.dtype)
                # HIGHEST forces fp32 contract precision, which Mosaic
                # rejects for bf16 operands; bf16 inputs with an f32
                # preferred type already accumulate exactly (onehot rows)
                precision = (
                    jax.lax.Precision.HIGHEST
                    if msg_scratch.dtype == jnp.float32
                    else jax.lax.Precision.DEFAULT
                )
                acc = acc + jax.lax.dot_general(
                    onehot,
                    msg_scratch[slot, r * 128 : (r + 1) * 128, :],
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=precision,
                )
            out_ref[:] += acc
            return 0

        jax.lax.fori_loop(c0, c1, body, 0)


def _scatter_sorted(
    msgs: jnp.ndarray,
    edge_dst: jnp.ndarray,
    num_nodes: int,
    interpret: bool = False,
    block_starts: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """msgs may be float32 or bfloat16 — bf16 halves the DMA bytes (the
    kernel's bound) while the MXU accumulates in f32 either way.

    ``block_starts`` (the blocked layout's host-precomputed per-128-dst
    extents, graph/snapshot.py ``edge_block_starts_from``) replaces the
    on-device binary search: the SAME scalar-prefetch vector, computed
    once at window close instead of per dispatch. Entries agree with
    the searchsorted values on every real edge; the one difference is
    the final sentinel (``n_edges``, not ``e_pad``), so the kernel's
    last dst block skips the chunks holding only bucket padding — pad
    edges stop accumulating into the masked last node row, exactly the
    blocked XLA fallback's frontier discipline."""
    e, f = msgs.shape
    assert e % 128 == 0 and num_nodes % TILE_N == 0, (
        f"pad edges/nodes to 128/{TILE_N} multiples (GraphBatch buckets do)"
    )
    n_blocks = num_nodes // TILE_N
    if block_starts is None:
        boundaries = jnp.arange(0, num_nodes + 1, TILE_N, dtype=jnp.int32)
        row_start = jnp.searchsorted(edge_dst, boundaries).astype(jnp.int32)
    else:
        row_start = block_starts.astype(jnp.int32)
    if e % TILE_E != 0:
        # bucket sizes are 128-multiples; round the edge axis up to TILE_E
        pad = TILE_E - e % TILE_E
        msgs = jnp.pad(msgs, ((0, pad), (0, 0)))
        edge_dst = jnp.pad(edge_dst, (0, pad), constant_values=num_nodes - 1)
        e = e + pad
    dst2d = edge_dst.reshape(e // 128, 128).astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # msgs stay in HBM; DMA'd
            pl.BlockSpec(memory_space=pl.ANY),  # dst ids
        ],
        out_specs=pl.BlockSpec(
            (TILE_N, f), lambda i, *_: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, TILE_E, f), msgs.dtype),
            pltpu.VMEM((2, _DST_ROWS, 128), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 1 + _DST_ROWS)),
        ],
    )
    itemsize = msgs.dtype.itemsize
    return pl.pallas_call(
        _scatter_kernel,
        out_shape=jax.ShapeDtypeStruct((num_nodes, f), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * e * TILE_N * f,
            bytes_accessed=e * f * itemsize + e * 4 + num_nodes * f * 4,
            transcendentals=0,
        ),
    )(row_start, msgs, dst2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def scatter_sum_sorted(msgs, edge_dst, num_nodes, out_dtype=None, block_starts=None):
    """out[d] = Σ_{e: dst[e]=d} msgs[e] for arbitrary per-edge messages
    (models add edge features/type embeddings before scattering).
    ``out_dtype=None`` returns the input dtype (one rounding of the f32
    MXU accumulator for bf16 inputs); pass ``jnp.float32`` where the sum
    feeds a normalization and that rounding matters
    (``segment_sum_accurate``). ``block_starts`` feeds the blocked
    layout's precomputed extents straight into the scalar prefetch —
    see ``_scatter_sorted``."""
    return _scatter_fwd_impl(msgs, edge_dst, num_nodes, out_dtype, block_starts)


def _scatter_fwd_impl(msgs, edge_dst, num_nodes, out_dtype=None, block_starts=None):
    dtype = msgs.dtype if out_dtype is None else jnp.dtype(out_dtype)
    if msgs.dtype not in (jnp.float32, jnp.bfloat16):
        msgs = msgs.astype(jnp.float32)
    f = msgs.shape[1]
    f_pad = ((f + 127) // 128) * 128
    if f_pad != f:
        msgs = jnp.pad(msgs, ((0, 0), (0, f_pad - f)))
    interpret = jax.default_backend() != "tpu"
    out = _scatter_sorted(
        msgs, edge_dst, num_nodes, interpret=interpret, block_starts=block_starts
    )
    return out[:, :f].astype(dtype)


def _scatter_vjp_fwd(msgs, edge_dst, num_nodes, out_dtype, block_starts=None):
    # residuals must be jax types: carry the input dtype as a 0-size token
    return (
        _scatter_fwd_impl(msgs, edge_dst, num_nodes, out_dtype, block_starts),
        (edge_dst, jnp.zeros((0,), msgs.dtype)),
    )


def _scatter_vjp_bwd(num_nodes, out_dtype, residuals, g):
    edge_dst, dtype_token = residuals
    # the extents are integer metadata — no cotangent, like edge_dst
    return (g[edge_dst].astype(dtype_token.dtype), None, None)


scatter_sum_sorted.defvjp(_scatter_vjp_fwd, _scatter_vjp_bwd)


# ---------------------------------------------------------------------------
# Sorted segment expand: out[e] = v[dst[e]] for dst-SORTED edges.
#
# An XLA row gather is row-op bound (~9 ns/row on v5e — measured identical
# for f32/bf16, sorted/unsorted, and even a 9-row table), so a [1M]-edge
# gather costs ~9 ms no matter what. For dst-sorted edges the rows needed
# by each TILE_E-edge chunk lie in the contiguous window
# [dst[c·T], dst[(c+1)·T]] — DMA 128-row windows of v and expand with a
# one-hot MXU matmul. Total DMAs ≈ E/TILE_E + N/128 instead of one row op
# per edge. The op is linear; its VJP is the scatter kernel.
# ---------------------------------------------------------------------------


def _expand_kernel(row_lo_ref, v_hbm, dst_hbm, out_ref, v_scratch, dst_scratch, sems):
    c = pl.program_id(0)
    lo = (row_lo_ref[c] // 128) * 128  # align the window start
    hi = row_lo_ref[c + 1]  # first dst row of the next chunk bounds this one
    nw = (hi - lo) // 128 + 1

    for r in range(_DST_ROWS):
        pltpu.make_async_copy(
            dst_hbm.at[pl.ds(c * _DST_ROWS + r, 1), :],
            dst_scratch.at[pl.ds(r, 1)],
            sems.at[2, r],
        ).start()

    def win_dma(slot, w):
        return pltpu.make_async_copy(
            v_hbm.at[pl.ds(lo + w * 128, 128), :],
            v_scratch.at[slot],
            sems.at[slot, 0],
        )

    win_dma(0, 0).start()
    for r in range(_DST_ROWS):
        pltpu.make_async_copy(
            dst_hbm.at[pl.ds(c * _DST_ROWS + r, 1), :],
            dst_scratch.at[pl.ds(r, 1)],
            sems.at[2, r],
        ).wait()

    precision = (
        jax.lax.Precision.HIGHEST
        if v_scratch.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )

    out_ref[:] = jnp.zeros_like(out_ref)

    def body(w, _):
        slot = jax.lax.rem(w, 2)

        @pl.when(w + 1 < nw)
        def _():
            win_dma(1 - slot, w + 1).start()

        win_dma(slot, w).wait()
        win0 = lo + w * 128
        for r in range(_DST_ROWS):
            dst_local = dst_scratch[r, :].reshape(128, 1) - win0
            onehot = (
                dst_local == jax.lax.broadcasted_iota(jnp.int32, (128, 128), 1)
            ).astype(v_scratch.dtype)
            contrib = jax.lax.dot_general(
                onehot,
                v_scratch[slot],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=precision,
            )
            out_ref[r * 128 : (r + 1) * 128, :] += contrib.astype(out_ref.dtype)
        return 0

    jax.lax.fori_loop(0, nw, body, 0)


def _expand_sorted(v: jnp.ndarray, edge_dst: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    n, f = v.shape
    e = edge_dst.shape[0]
    assert e % TILE_E == 0 and n % 128 == 0
    n_chunks = e // TILE_E
    dst2d = edge_dst.reshape(e // 128, 128).astype(jnp.int32)
    # per-chunk window start: first dst of each chunk; the sentinel keeps
    # the last chunk's window end in range
    lo = edge_dst[:: TILE_E].astype(jnp.int32)
    row_lo = jnp.concatenate([lo, jnp.asarray([n - 1], jnp.int32)])
    row_lo = jnp.minimum(row_lo, n - 128)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # v stays in HBM; DMA'd
            pl.BlockSpec(memory_space=pl.ANY),  # dst ids
        ],
        out_specs=pl.BlockSpec(
            (TILE_E, f), lambda c, *_: (c, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 128, f), v.dtype),
            pltpu.VMEM((_DST_ROWS, 128), jnp.int32),
            pltpu.SemaphoreType.DMA((3, max(2, _DST_ROWS))),
        ],
    )
    return pl.pallas_call(
        _expand_kernel,
        out_shape=jax.ShapeDtypeStruct((e, f), v.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * e * 128 * f,
            bytes_accessed=e * f * v.dtype.itemsize * 2 + e * 4,
            transcendentals=0,
        ),
    )(row_lo, v, dst2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_expand_sorted(v, edge_dst, num_nodes):
    """out[e] = v[dst[e]] with edges sorted by dst (the GraphBatch
    layout). ``num_nodes`` rides along for the backward scatter."""
    return _expand_fwd_impl(v, edge_dst)


def _expand_fwd_impl(v, edge_dst):
    dtype = v.dtype
    if dtype not in (jnp.float32, jnp.bfloat16):
        v = v.astype(jnp.float32)
    f = v.shape[1]
    f_pad = ((f + 127) // 128) * 128
    if f_pad != f:
        v = jnp.pad(v, ((0, 0), (0, f_pad - f)))
    e = edge_dst.shape[0]
    e_pad = ((e + TILE_E - 1) // TILE_E) * TILE_E
    if e_pad != e:
        edge_dst = jnp.pad(edge_dst, (0, e_pad - e), constant_values=v.shape[0] - 1)
    interpret = jax.default_backend() != "tpu"
    out = _expand_sorted(v, edge_dst, interpret=interpret)
    return out[:e, :f].astype(dtype)


def _expand_vjp_fwd(v, edge_dst, num_nodes):
    return _expand_fwd_impl(v, edge_dst), (edge_dst,)


def _expand_vjp_bwd(num_nodes, residuals, g):
    (edge_dst,) = residuals
    # dv[d] = Σ_{e: dst[e]=d} g[e] — exactly the dst-sorted scatter
    return (scatter_sum_sorted(g, edge_dst, num_nodes), None)


segment_expand_sorted.defvjp(_expand_vjp_fwd, _expand_vjp_bwd)


# ---------------------------------------------------------------------------
# Banded gather: out[e] = v[ids[e]] for ids that are UNSORTED but mostly
# cluster per TILE_E chunk — the src-side gather after the
# cluster_renumber layout pass (graph/builder.py). Edges are dst-sorted;
# with community structure + renumbering, MOST sources referenced by a
# chunk of consecutive edges sit near each other in the node table, but
# real service maps always carry cross-team strays (even 1 stray per
# chunk blows a [min,max] band out to the whole table — measured 70×
# slower than the XLA gather at 10% cross-team traffic). So the kernel
# covers a FIXED BAND_WINDOWS-wide band centered on each chunk's median
# window: in-band rows expand via one-hot MXU matmuls (out-of-band ids
# one-hot to zero), and the host fixes up the stragglers with an XLA
# row-gather over a static budget of positions, falling back to the
# plain gather if a batch overflows the budget. DMA count is a flat
# BAND_WINDOWS/chunk and the straggler cost is ≤ budget·~9ns — on
# uniform-random ids nearly everything is a straggler and the XLA
# gather is strictly better; callers gate on the measured straggler
# fraction (ARCHITECTURE.md §3b).
# ---------------------------------------------------------------------------


def _banded_gather_kernel(
    band, lo_ref, v_hbm, ids_hbm, out_ref, v_scratch, id_scratch, sems
):
    # ``band`` is a static Python int (the fixed window count every chunk
    # covers), so the window loop below unrolls and double-buffer slots
    # are compile-time constants.
    c = pl.program_id(0)
    # lo_ref carries the window INDEX (row//128), not the row base: the
    # HBM slice offset is then (index)*128, whose tile alignment Mosaic
    # can prove — a raw runtime row offset is rejected ("tile index in
    # dimension 0 is divisible by the tiling") even when it is a
    # multiple of 128 by construction
    lo_w = lo_ref[c]

    for r in range(_DST_ROWS):
        pltpu.make_async_copy(
            ids_hbm.at[pl.ds(c * _DST_ROWS + r, 1), :],
            id_scratch.at[pl.ds(r, 1)],
            sems.at[2, r],
        ).start()

    def win_dma(slot, w):
        return pltpu.make_async_copy(
            v_hbm.at[pl.ds((lo_w + w) * 128, 128), :],
            v_scratch.at[slot],
            sems.at[slot, 0],
        )

    win_dma(0, 0).start()
    for r in range(_DST_ROWS):
        pltpu.make_async_copy(
            ids_hbm.at[pl.ds(c * _DST_ROWS + r, 1), :],
            id_scratch.at[pl.ds(r, 1)],
            sems.at[2, r],
        ).wait()

    precision = (
        jax.lax.Precision.HIGHEST
        if v_scratch.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )

    out_ref[:] = jnp.zeros_like(out_ref)

    for w in range(band):
        slot = w % 2
        if w + 1 < band:
            win_dma(1 - slot, w + 1).start()
        win_dma(slot, w).wait()
        win0 = (lo_w + w) * 128
        for r in range(_DST_ROWS):
            id_local = id_scratch[r, :].reshape(128, 1) - win0
            onehot = (
                id_local == jax.lax.broadcasted_iota(jnp.int32, (128, 128), 1)
            ).astype(v_scratch.dtype)
            contrib = jax.lax.dot_general(
                onehot,
                v_scratch[slot],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=precision,
            )
            out_ref[r * 128 : (r + 1) * 128, :] += contrib.astype(out_ref.dtype)


def _gather_banded(v: jnp.ndarray, ids: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Hybrid banded gather: fixed-width Pallas band + XLA straggler
    fix-up, with a whole-batch XLA fallback when stragglers overflow the
    budget (correctness never depends on the layout actually clustering).
    """
    n, f = v.shape
    e = ids.shape[0]
    assert e % TILE_E == 0 and n % 128 == 0
    n_chunks = e // TILE_E
    ids = ids.astype(jnp.int32)
    n_windows = n // 128
    band = min(BAND_WINDOWS, n_windows)
    win = ids // 128
    per_chunk = win.reshape(n_chunks, TILE_E)
    # median window per chunk: robust to strays, unlike min/max
    med = jnp.median(per_chunk, axis=1).astype(jnp.int32)
    lo_w = jnp.clip(med - band // 2, 0, n_windows - band)
    lo_e = jnp.repeat(lo_w, TILE_E)  # per-edge band base
    in_band = (win >= lo_e) & (win < lo_e + band)
    n_strag = jnp.sum(~in_band)
    # static straggler budget: 1/8 of the edge axis (community maps run
    # ~10% cross-team); overflow → cond takes the plain-gather branch
    budget = int(min(e, max(TILE_E, e // 8)))

    def plain(_):
        return v[ids]

    def hybrid(_):
        out = _banded_call(v, ids, lo_w, band, interpret)
        pos = jnp.nonzero(~in_band, size=budget, fill_value=e)[0]
        rows = v[ids[jnp.minimum(pos, e - 1)]]
        # fill positions point one past the end; "drop" discards them
        return out.at[pos].set(rows, mode="drop")

    return jax.lax.cond(n_strag <= budget, hybrid, plain, None)


def _banded_call(v, ids, lo_w, band, interpret):
    n, f = v.shape
    e = ids.shape[0]
    n_chunks = e // TILE_E
    ids2d = ids.reshape(e // 128, 128)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # v stays in HBM; DMA'd
            pl.BlockSpec(memory_space=pl.ANY),  # ids
        ],
        out_specs=pl.BlockSpec(
            (TILE_E, f), lambda c, *_: (c, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 128, f), v.dtype),
            pltpu.VMEM((_DST_ROWS, 128), jnp.int32),
            pltpu.SemaphoreType.DMA((3, max(2, _DST_ROWS))),
        ],
    )
    return pl.pallas_call(
        functools.partial(_banded_gather_kernel, band),
        out_shape=jax.ShapeDtypeStruct((e, f), v.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * e * band * 128 * f,
            bytes_accessed=e * f * v.dtype.itemsize * 2 + e * 4,
            transcendentals=0,
        ),
    )(lo_w, v, ids2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def gather_rows_banded(v, ids, num_nodes):
    """out[e] = v[ids[e]] for unsorted ids whose per-chunk majority
    clusters (post-cluster_renumber src gathers); strays are fixed up
    with an XLA row gather. ``num_nodes`` rides along for the backward
    scatter."""
    return _banded_fwd_impl(v, ids)


def _banded_fwd_impl(v, ids):
    dtype = v.dtype
    if dtype not in (jnp.float32, jnp.bfloat16):
        v = v.astype(jnp.float32)
    f = v.shape[1]
    f_pad = ((f + 127) // 128) * 128
    if f_pad != f:
        v = jnp.pad(v, ((0, 0), (0, f_pad - f)))
    e = ids.shape[0]
    e_pad = ((e + TILE_E - 1) // TILE_E) * TILE_E
    if e_pad != e:
        # pad ids with the last real id: the pad chunk's band collapses
        # onto one window instead of dragging in row 0
        fill = ids[-1] if e > 0 else jnp.int32(0)
        ids = jnp.concatenate(
            [ids, jnp.full((e_pad - e,), fill, ids.dtype)]
        )
    interpret = jax.default_backend() != "tpu"
    out = _gather_banded(v, ids, interpret=interpret)
    return out[:e, :f].astype(dtype)


def _banded_vjp_fwd(v, ids, num_nodes):
    return _banded_fwd_impl(v, ids), (ids,)


def _banded_vjp_bwd(num_nodes, residuals, g):
    (ids,) = residuals
    # dv[i] = Σ_{e: ids[e]=i} g[e] — ids are unsorted, XLA scatter
    dv = jax.ops.segment_sum(
        g.astype(jnp.float32), ids, num_segments=num_nodes
    ).astype(g.dtype)
    return (dv, None)


gather_rows_banded.defvjp(_banded_vjp_fwd, _banded_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def pallas_gather_scatter_sum(x, edge_src, edge_dst, num_nodes, edge_weight=None):
    """out[d] = Σ_{e: dst[e]=d} w[e]·x[src[e]], edges sorted by dst."""
    return _forward(x, edge_src, edge_dst, num_nodes, edge_weight)


def _forward(x, edge_src, edge_dst, num_nodes, edge_weight):
    msgs = x[edge_src]
    if msgs.dtype not in (jnp.float32, jnp.bfloat16):
        msgs = msgs.astype(jnp.float32)
    if edge_weight is not None:
        msgs = msgs * edge_weight[:, None].astype(msgs.dtype)
    # VMEM slices must be 128-lane aligned: pad the feature dim up
    f = msgs.shape[1]
    f_pad = ((f + 127) // 128) * 128
    if f_pad != f:
        msgs = jnp.pad(msgs, ((0, 0), (0, f_pad - f)))
    interpret = jax.default_backend() != "tpu"
    out = _scatter_sorted(msgs, edge_dst, num_nodes, interpret=interpret)
    return out[:, :f].astype(x.dtype)


def _fwd(x, edge_src, edge_dst, num_nodes, edge_weight):
    return _forward(x, edge_src, edge_dst, num_nodes, edge_weight), (
        x,
        edge_src,
        edge_dst,
        edge_weight,
    )


def _bwd(num_nodes, residuals, g):
    x, edge_src, edge_dst, edge_weight = residuals
    g_edges = g[edge_dst].astype(jnp.float32)  # [E, F]
    w = (
        edge_weight[:, None].astype(jnp.float32)
        if edge_weight is not None
        else jnp.float32(1.0)
    )
    # dx[s] = Σ_{e: src[e]=s} w[e]·g[dst[e]] — not src-sorted, XLA scatter
    dx = jax.ops.segment_sum(g_edges * w, edge_src, num_segments=x.shape[0]).astype(x.dtype)
    if edge_weight is not None:
        dw = jnp.sum(x[edge_src].astype(jnp.float32) * g_edges, axis=1).astype(
            edge_weight.dtype
        )
    else:
        dw = None
    return dx, None, None, dw


pallas_gather_scatter_sum.defvjp(_fwd, _bwd)
