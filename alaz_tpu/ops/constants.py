"""Kernel tiling constants shared with jax-free modules.

The Pallas kernels (ops/pallas_segment.py) chunk edges at ``TILE_E`` and
DMA node-table rows in ``DMA_WINDOW``-row windows; host-side cost models
(graph/builder.src_band_windows — the windows.src_band_windows gauge)
must use the SAME values or they steer operators to the wrong
src-gather choice. This module keeps them importable without jax.
"""

TILE_E = 512  # edges per kernel chunk (multiple of 128)
# Fixed band width (in DMA_WINDOW-row windows) the hybrid banded gather
# covers around each chunk's median src window; ids outside the band are
# fixed up host-side by an XLA row gather over a static 1/8-of-edges
# straggler budget. 4 windows = 512 rows comfortably covers one
# renumbered team/community; widening it scales kernel FLOPs linearly.
BAND_WINDOWS = 4
# Node-table rows per DMA window. STRUCTURAL: this equals the MXU width
# (128) and the kernels' VMEM scratch/one-hot shapes are written against
# the literal; it is exported for cost models to read, not to retune.
DMA_WINDOW = 128
