"""Kernel tiling constants shared with jax-free modules.

The Pallas kernels (ops/pallas_segment.py) chunk edges at ``TILE_E`` and
DMA node-table rows in ``DMA_WINDOW``-row windows; host-side cost models
(graph/builder.src_band_windows — the windows.src_band_windows gauge)
must use the SAME values or they steer operators to the wrong
src-gather choice. This module keeps them importable without jax.
"""

TILE_E = 512  # edges per kernel chunk (multiple of 128)
DMA_WINDOW = 128  # node-table rows per DMA window (= MXU width)
