"""Structured logging — the zerolog analog (reference log/log.go).

Honors the same env contract: ``LOG_LEVEL`` (debug|info|warn|error),
``DISABLE_LOGS``, and ``LOG_CONTEXT_KEY`` (filter log records to a single
pid context, log.go:55-75).
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


class _PidContextFilter(logging.Filter):
    """When LOG_CONTEXT_KEY is set, only pass records whose ``pid`` extra
    matches — the log.go:55-75 behavior."""

    def __init__(self, pid: str):
        super().__init__()
        self.pid = pid

    def filter(self, record: logging.LogRecord) -> bool:
        pid = getattr(record, "pid", None)
        return pid is None or str(pid) == self.pid


def get_logger(name: str = "alaz_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if getattr(logger, "_alaz_configured", False):
        return logger
    logger._alaz_configured = True  # type: ignore[attr-defined]

    if os.environ.get("DISABLE_LOGS", "").lower() in ("1", "true"):
        logger.addHandler(logging.NullHandler())
        logger.propagate = False
        return logger

    level = _LEVELS.get(os.environ.get("LOG_LEVEL", "info").lower(), logging.INFO)
    logger.setLevel(level)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(
            logging.Formatter(
                '{"ts":"%(asctime)s","level":"%(levelname)s","logger":"%(name)s","msg":"%(message)s"}'
            )
        )
        logger.addHandler(h)
    ctx = os.environ.get("LOG_CONTEXT_KEY")
    if ctx:
        logger.addFilter(_PidContextFilter(ctx))
    logger.propagate = False
    return logger


logger = get_logger()
