// ThreadSanitizer harness for the ingest core: one producer pushing
// records across several (including out-of-order) windows while a
// consumer drains and closes windows concurrently. Built by `make tsan`
// with -fsanitize=thread; exits 0 iff the aggregate counts balance and
// TSAN reports nothing (TSAN itself fails the process on a race when run
// with halt_on_error, and prints WARNINGs otherwise — the pytest wrapper
// checks both).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

// Source-stamp marker (the Makefile passes -DALZ_BIN_STAMP with the
// sha256 prefix of ingest.cc + tsan_test.cc concatenated): the alazspec
// staleness guard byte-scans the binary for it, so a tsan_test built
// from a different ingest core than the one checked in is flagged
// (ROADMAP ALZ020 follow-up).
#ifndef ALZ_BIN_STAMP
#define ALZ_BIN_STAMP "unstamped"
#endif
__attribute__((used)) static const char kAlzSourceStamp[] =
    "ALZ_SOURCE_STAMP:" ALZ_BIN_STAMP;

extern "C" {
struct AlzRecord {
  int64_t start_time_ms;
  uint64_t latency_ns;
  int32_t from_uid;
  int32_t to_uid;
  uint32_t status;
  uint8_t from_type;
  uint8_t to_type;
  uint8_t protocol;
  uint8_t flags;
};

void* alz_create(int64_t, uint32_t, uint32_t, uint32_t);
void alz_destroy(void*);
uint32_t alz_push(void*, const AlzRecord*, uint32_t);
int64_t alz_drain(void*);
int64_t alz_current_window(void*);
uint64_t alz_ring_dropped(void*);
uint64_t alz_late_dropped(void*);
uint64_t alz_acc_dropped(void*);
int32_t alz_close_window(void*, uint32_t, int64_t*, int32_t*, int32_t*,
                         uint8_t*, uint64_t*, uint64_t*, uint64_t*, uint32_t*,
                         uint32_t*, uint32_t*);
}

namespace {

constexpr uint32_t kBufCap = 4096;
constexpr int kRecords = 200000;
constexpr int kWindows = 20;

struct Buffers {
  std::vector<int32_t> src = std::vector<int32_t>(kBufCap);
  std::vector<int32_t> dst = std::vector<int32_t>(kBufCap);
  std::vector<uint8_t> proto = std::vector<uint8_t>(kBufCap);
  std::vector<uint64_t> count = std::vector<uint64_t>(kBufCap);
  std::vector<uint64_t> lat_sum = std::vector<uint64_t>(kBufCap);
  std::vector<uint64_t> lat_max = std::vector<uint64_t>(kBufCap);
  std::vector<uint32_t> err5 = std::vector<uint32_t>(kBufCap);
  std::vector<uint32_t> err4 = std::vector<uint32_t>(kBufCap);
  std::vector<uint32_t> tls = std::vector<uint32_t>(kBufCap);
};

uint64_t close_one(void* ig, Buffers* b, int* windows_closed) {
  int64_t ws = 0;
  int32_t n = alz_close_window(
      ig, kBufCap, &ws, b->src.data(), b->dst.data(), b->proto.data(),
      b->count.data(), b->lat_sum.data(), b->lat_max.data(), b->err5.data(),
      b->err4.data(), b->tls.data());
  if (n < 0) return 0;
  *windows_closed += 1;
  uint64_t total = 0;
  for (int32_t i = 0; i < n; ++i) total += b->count[i];
  return total;
}

}  // namespace

int main() {
  void* ig = alz_create(/*window_ms=*/100, /*ring=*/1 << 14, /*edges=*/kBufCap,
                        /*nodes=*/4096);

  std::atomic<uint64_t> pushed{0};
  std::atomic<bool> done{false};

  std::thread producer([&] {
    AlzRecord rec;
    std::memset(&rec, 0, sizeof(rec));
    uint32_t state = 12345;
    for (int i = 0; i < kRecords; ++i) {
      state = state * 1664525u + 1013904223u;
      int64_t w = (i * kWindows) / kRecords;  // advancing windows...
      if ((state >> 16 & 7) == 0 && w > 0) w -= 1;  // ...with stragglers
      rec.start_time_ms = w * 100 + (state & 63);
      rec.latency_ns = state & 0xFFFF;
      rec.from_uid = static_cast<int32_t>(state % 50);
      rec.to_uid = static_cast<int32_t>((state >> 8) % 50);
      rec.status = (state & 15) == 0 ? 500 : 200;
      rec.protocol = state % 8;
      rec.flags = state & 1;
      pushed.fetch_add(alz_push(ig, &rec, 1), std::memory_order_relaxed);
    }
    done.store(true, std::memory_order_release);
  });

  Buffers bufs;
  uint64_t accumulated = 0;
  int windows_closed = 0;
  while (!done.load(std::memory_order_acquire)) {
    int64_t ready = alz_drain(ig);
    if (ready != INT64_MIN) accumulated += close_one(ig, &bufs, &windows_closed);
  }
  producer.join();
  // final flush: drain the remainder and close every open window
  while (alz_drain(ig) != INT64_MIN) accumulated += close_one(ig, &bufs, &windows_closed);
  while (alz_current_window(ig) != INT64_MIN)
    accumulated += close_one(ig, &bufs, &windows_closed);

  uint64_t late = alz_late_dropped(ig);
  uint64_t ring_drop = alz_ring_dropped(ig);
  uint64_t acc_drop = alz_acc_dropped(ig);
  uint64_t accounted = accumulated + late + acc_drop;
  std::printf(
      "pushed=%llu accumulated=%llu late=%llu ring_dropped=%llu acc_dropped=%llu windows=%d\n",
      (unsigned long long)pushed.load(), (unsigned long long)accumulated,
      (unsigned long long)late, (unsigned long long)ring_drop,
      (unsigned long long)acc_drop, windows_closed);
  alz_destroy(ig);
  if (accounted != pushed.load()) {
    std::fprintf(stderr, "FAIL: %llu accepted but %llu accounted\n",
                 (unsigned long long)pushed.load(), (unsigned long long)accounted);
    return 1;
  }
  // Under TSAN slowdown the ring drops aggressively and may skip whole
  // windows; the invariant is the balance above plus multi-window progress.
  if (windows_closed < 2) {
    std::fprintf(stderr, "FAIL: only %d windows closed\n", windows_closed);
    return 1;
  }
  std::puts("OK");
  return 0;
}
