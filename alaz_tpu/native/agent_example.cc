// Minimal out-of-process agent: ships pre-attributed AlzRecord edges to
// the service's ingest socket using the frame protocol documented in
// sources/ingest_server.py (16-byte header + packed records, one writev
// per batch, zero serialization). This is the reference integration for
// native capture agents — anything that can fill AlzRecord structs can
// feed the framework.
//
// Usage: agent_example <unix-socket-path> [n_records] [window_ms0]
// Built by `make agent` (not part of the default target).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>
#include <vector>

// Source-stamp marker (the Makefile passes -DALZ_BIN_STAMP with the
// sha256 prefix of agent_example.cc): executables can't be dlopen'd for
// an alz_source_hash() call, so the alazspec staleness guard byte-scans
// the binary for this marker instead (ROADMAP ALZ020 follow-up).
#ifndef ALZ_BIN_STAMP
#define ALZ_BIN_STAMP "unstamped"
#endif
__attribute__((used)) static const char kAlzSourceStamp[] =
    "ALZ_SOURCE_STAMP:" ALZ_BIN_STAMP;

struct AlzRecord {  // mirrors ingest.cc / NATIVE_RECORD_DTYPE (32 bytes)
  int64_t start_time_ms;
  uint64_t latency_ns;
  int32_t from_uid;
  int32_t to_uid;
  uint32_t status;
  uint8_t from_type;
  uint8_t to_type;
  uint8_t protocol;
  uint8_t flags;
};

struct FrameHeader {  // little-endian; matches ingest_server.FRAME_HEADER
  uint32_t magic;
  uint8_t kind;
  uint8_t tenant;  // fleet id (ISSUE 14); zero-init = the legacy tenant
  uint8_t pad[2];
  uint32_t count;
  uint32_t length;
};

static_assert(sizeof(AlzRecord) == 32, "wire record must be 32 bytes");
static_assert(sizeof(FrameHeader) == 16, "frame header must be 16 bytes");

constexpr uint32_t kMagic = 0x414C5A31;  // "ALZ1"
constexpr uint8_t kKindNative = 4;

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <socket-path> [n_records] [window_ms0]\n",
                 argv[0]);
    return 2;
  }
  const char* path = argv[1];
  unsigned long n_arg = argc > 2 ? std::strtoul(argv[2], nullptr, 10) : 1000;
  // header length is u32 bytes: cap so count * 32 cannot overflow it
  if (n_arg == 0 || n_arg > (UINT32_MAX / sizeof(AlzRecord))) {
    std::fprintf(stderr, "n_records out of range: %s\n", argv[2]);
    return 2;
  }
  uint32_t n = static_cast<uint32_t>(n_arg);
  int64_t t0 = argc > 3 ? std::atoll(argv[3]) : 1000;

  std::vector<AlzRecord> recs(n);
  uint32_t state = 42;
  for (uint32_t i = 0; i < n; ++i) {
    state = state * 1664525u + 1013904223u;
    AlzRecord& r = recs[i];
    std::memset(&r, 0, sizeof(r));
    r.start_time_ms = t0 + (i % 3) * 1000;  // three windows
    r.latency_ns = 1000 + (state & 0xFFFF);
    r.from_uid = static_cast<int32_t>(state % 20);
    r.to_uid = 100 + static_cast<int32_t>((state >> 8) % 8);
    r.status = (state & 31) == 0 ? 500 : 200;
    r.from_type = 1;  // pod
    r.to_type = 2;    // service
    r.protocol = 1 + state % 8;
    r.flags = state & 1;  // tls bit
  }

  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) {
    std::perror("socket");
    return 1;
  }
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("connect");
    return 1;
  }

  FrameHeader hdr{};
  hdr.magic = kMagic;
  hdr.kind = kKindNative;
  hdr.count = n;
  hdr.length = n * sizeof(AlzRecord);
  iovec iov[2] = {
      {&hdr, sizeof(hdr)},
      {recs.data(), recs.size() * sizeof(AlzRecord)},
  };
  ssize_t want = static_cast<ssize_t>(sizeof(hdr) + hdr.length);
  ssize_t sent = writev(fd, iov, 2);
  while (sent >= 0 && sent < want) {  // short writes on large batches
    size_t off = static_cast<size_t>(sent);
    const uint8_t* base;
    size_t remaining;
    if (off < sizeof(hdr)) {
      base = reinterpret_cast<const uint8_t*>(&hdr) + off;
      remaining = sizeof(hdr) - off;
      ssize_t k = write(fd, base, remaining);
      if (k < 0) break;
      sent += k;
      continue;
    }
    off -= sizeof(hdr);
    base = reinterpret_cast<const uint8_t*>(recs.data()) + off;
    remaining = hdr.length - off;
    ssize_t k = write(fd, base, remaining);
    if (k < 0) break;
    sent += k;
  }
  close(fd);
  if (sent != want) {
    std::perror("write");
    return 1;
  }
  std::printf("sent %u records (%u bytes)\n", n, hdr.length);
  return 0;
}
