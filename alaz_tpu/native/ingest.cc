// Host ingest plane: lock-free ring buffer + windowed edge accumulator.
//
// This is the native core of the graph batcher (SURVEY §2.1 "TPU-native
// equivalents": the C++ analog of the reference's kernel-side event plane,
// playing the role l7.c's maps play — bounded, drop-not-block, fixed-size
// records). Producers push resolved edge records into a SPSC ring; the
// consumer drains into per-window accumulators keyed
// (from_uid, to_uid, protocol); closed windows export COO arrays +
// per-node tables directly into caller-provided (numpy) buffers.
//
// Window semantics mirror WindowedGraphStore (graph/builder.py): multiple
// windows may be open at once, a window becomes ready to close when the
// watermark (max window id seen) passes it, and rows for already-closed
// windows are dropped as late (the aggregator retry queue legitimately
// delivers old-window rows after new-window rows — reference requeue
// behavior /root/reference/aggregator/data.go:404-437).
//
// Build: make -C alaz_tpu/native   → libalaz_ingest.so (ctypes-loaded by
// alaz_tpu/graph/native.py; the pure-numpy GraphBuilder is the fallback).
// `make tsan` additionally builds a -fsanitize=thread test binary.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

// Stamped by the Makefile with the sha256 prefix of this source file so
// alazspec (tools/alazspec) can flag a .so built from a different
// ingest.cc than the one checked in (the classic "stale kernel object"
// failure mode of the reference's bpf2go artifacts).
#ifndef ALZ_SOURCE_HASH
#define ALZ_SOURCE_HASH "unstamped"
#endif

// Byte-scannable twin of alz_source_hash() for builds that cannot be
// dlopen'd from the checking process: the ASan/UBSan shared objects
// (loading them requires the sanitizer runtime preloaded), like
// tsan_test/agent_example before them, carry the marker in .rodata so
// check_binary_stamps can flag a stale sanitizer build without loading
// it. Executable builds that link this file (tsan_test) define
// ALZ_BIN_STAMP and emit their OWN marker covering every linked source;
// suppress this one there so the byte scan finds exactly one stamp.
#ifndef ALZ_BIN_STAMP
__attribute__((used)) static const char kAlzSourceStamp[] =
    "ALZ_SOURCE_STAMP:" ALZ_SOURCE_HASH;
#endif

extern "C" {

// Mirror of events/schema.py L7Protocol (the reference's
// BPF_L7_PROTOCOL_* constants, l7.go:19-28). The `protocol` byte of
// AlzRecord and the one-hot clamp in alz_close_window_feats are typed
// against THIS enum; alazspec diffs it value-for-value against the
// Python enum, so a protocol added on one side only fails tier-1
// instead of silently folding into a neighbor's one-hot slot.
enum AlzProtocol {
  ALZ_PROTO_UNKNOWN = 0,
  ALZ_PROTO_HTTP = 1,
  ALZ_PROTO_AMQP = 2,
  ALZ_PROTO_POSTGRES = 3,
  ALZ_PROTO_HTTP2 = 4,
  ALZ_PROTO_REDIS = 5,
  ALZ_PROTO_KAFKA = 6,
  ALZ_PROTO_MYSQL = 7,
  ALZ_PROTO_MONGO = 8,
};

// One-hot clamp bound for the feature pass below. Kept as a literal
// (not ALZ_PROTO_MONGO + 1) so a 10th protocol added to both enums but
// not here still fails tier-1: alazspec checks kProtoCount ==
// len(L7Protocol), which a named-member clamp could never catch.
constexpr uint32_t kProtoCount = 9;

// 32-byte wire record; mirrored by NATIVE_RECORD_DTYPE in graph/native.py.
// flags: bit0 = tls, bit1 = failed (request not completed)
struct AlzRecord {
  int64_t start_time_ms;
  uint64_t latency_ns;
  int32_t from_uid;
  int32_t to_uid;
  uint32_t status;
  uint8_t from_type;
  uint8_t to_type;
  uint8_t protocol;
  uint8_t flags;
};

struct EdgeSlot {
  int32_t from_uid;
  int32_t to_uid;
  uint8_t protocol;
  uint8_t _pad;
  int32_t src_slot;
  int32_t dst_slot;
  uint64_t count;
  uint64_t lat_sum;
  uint64_t lat_max;
  uint32_t err5;
  uint32_t err4;
  uint32_t tls_cnt;
};

struct NodeSlot {
  int32_t uid;
  int32_t slot;  // dense node index
  uint8_t type;
  uint8_t used;
};

// ---------------------------------------------------------------------------
// L7 engine wire mirrors (ISSUE 16). These are byte-for-byte images of the
// PACKED numpy dtypes the Python plane pins (events/schema.py
// L7_EVENT_DTYPE, datastore/dto.py REQUEST_DTYPE) — the same arrays the
// shm_ring ABI already carries between shard processes, so a shard worker
// can hand a ring-slot view straight to alz_process_l7 with zero per-row
// Python work. graph/native.py refuses the .so at load when the layout
// strings below disagree with dtype_layout() (the AlzRecord precedent).
// ---------------------------------------------------------------------------

#pragma pack(push, 1)

struct AlzL7Event {
  uint32_t pid;
  uint64_t fd;
  uint64_t write_time_ns;
  uint64_t duration_ns;
  uint8_t protocol;
  uint8_t method;
  uint8_t tls;
  uint8_t failed;
  uint32_t status;
  uint32_t payload_size;
  uint8_t payload_read_complete;
  uint32_t tid;
  uint32_t seq;
  int16_t kafka_api_version;
  uint32_t mysql_prep_stmt_id;
  uint32_t saddr;
  uint16_t sport;
  uint32_t daddr;
  uint16_t dport;
  uint64_t event_read_time_ns;
  uint8_t payload[256];
};
static_assert(sizeof(AlzL7Event) == 331, "L7_EVENT_DTYPE mirror drifted");

struct AlzRequest {
  int64_t start_time_ms;
  uint64_t latency_ns;
  uint32_t from_ip;
  uint8_t from_type;
  int32_t from_uid;
  uint16_t from_port;
  uint32_t to_ip;
  uint8_t to_type;
  int32_t to_uid;
  uint16_t to_port;
  uint8_t protocol;
  uint8_t tls;
  uint8_t completed;
  uint32_t status_code;
  int32_t fail_reason;
  uint8_t method;
  int32_t path;
};
static_assert(sizeof(AlzRequest) == 54, "REQUEST_DTYPE mirror drifted");

#pragma pack(pop)

}  // extern "C"

namespace {

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

class NodeTable {
 public:
  explicit NodeTable(uint32_t cap_pow2) : mask_(cap_pow2 - 1), slots_(cap_pow2) {}

  // uid -> dense slot (insert on miss); -1 when full
  int32_t get_or_add(int32_t uid, uint8_t type, std::vector<int32_t>* uids,
                     std::vector<uint8_t>* types) {
    uint64_t h = mix64(static_cast<uint64_t>(static_cast<uint32_t>(uid)));
    for (uint32_t probe = 0; probe <= mask_; ++probe) {
      NodeSlot& s = slots_[(h + probe) & mask_];
      if (!s.used) {
        s.used = 1;
        s.uid = uid;
        s.type = type;
        s.slot = static_cast<int32_t>(uids->size());
        uids->push_back(uid);
        types->push_back(type);
        return s.slot;
      }
      if (s.uid == uid) return s.slot;
    }
    return -1;
  }

 private:
  uint32_t mask_;
  std::vector<NodeSlot> slots_;
};

// One open window's edge accumulator: a dense append-only arena of
// EdgeSlots plus an open-addressing index (key -> arena position). The
// index rehashes as the arena grows, so straggler windows stay tiny while
// the hot window grows to full size; recycling keeps arena capacity.
class WindowAcc {
 public:
  WindowAcc() { reset_index(64); }

  void open(int64_t window_id) {
    window_id_ = window_id;
    edges_.clear();
    if (index_.size() > 64 && edges_.capacity() < index_.size() / 4) {
      reset_index(64);  // shrink index for a recycled straggler table
    } else {
      std::memset(index_.data(), 0, index_.size() * sizeof(IndexSlot));
    }
  }

  int64_t window_id() const { return window_id_; }
  const std::vector<EdgeSlot>& edges() const { return edges_; }

  // nullptr when the caller-imposed edge cap is reached
  EdgeSlot* get_or_add(int32_t fu, int32_t tu, uint8_t proto, uint32_t max_edges) {
    if (edges_.size() * 2 >= index_.size()) grow_index();
    uint64_t h = mix64((static_cast<uint64_t>(static_cast<uint32_t>(fu)) << 32) ^
                       (static_cast<uint64_t>(static_cast<uint32_t>(tu)) << 8) ^ proto);
    uint32_t mask = static_cast<uint32_t>(index_.size() - 1);
    for (uint32_t probe = 0; probe <= mask; ++probe) {
      IndexSlot& s = index_[(h + probe) & mask];
      if (!s.used) {
        if (edges_.size() >= max_edges) return nullptr;
        s.used = 1;
        s.from_uid = fu;
        s.to_uid = tu;
        s.protocol = proto;
        s.idx = static_cast<uint32_t>(edges_.size());
        edges_.push_back(EdgeSlot{});
        EdgeSlot& e = edges_.back();
        std::memset(&e, 0, sizeof(e));
        e.from_uid = fu;
        e.to_uid = tu;
        e.protocol = proto;
        return &e;
      }
      if (s.from_uid == fu && s.to_uid == tu && s.protocol == proto) {
        return &edges_[s.idx];
      }
    }
    return nullptr;
  }

 private:
  struct IndexSlot {
    int32_t from_uid;
    int32_t to_uid;
    uint32_t idx;
    uint8_t protocol;
    uint8_t used;
  };

  void reset_index(uint32_t cap) {
    index_.assign(cap, IndexSlot{});
  }

  void grow_index() {
    std::vector<IndexSlot> old = std::move(index_);
    reset_index(static_cast<uint32_t>(old.size() * 2));
    uint32_t mask = static_cast<uint32_t>(index_.size() - 1);
    for (const IndexSlot& s : old) {
      if (!s.used) continue;
      uint64_t h = mix64(
          (static_cast<uint64_t>(static_cast<uint32_t>(s.from_uid)) << 32) ^
          (static_cast<uint64_t>(static_cast<uint32_t>(s.to_uid)) << 8) ^ s.protocol);
      for (uint32_t probe = 0; probe <= mask; ++probe) {
        IndexSlot& d = index_[(h + probe) & mask];
        if (!d.used) {
          d = s;
          break;
        }
      }
    }
  }

  int64_t window_id_ = INT64_MIN;
  std::vector<EdgeSlot> edges_;
  std::vector<IndexSlot> index_;
};

constexpr int kMaxOpenWindows = 8;

struct Ingest {
  // SPSC ring
  std::vector<AlzRecord> ring;
  uint32_t ring_mask;
  std::atomic<uint64_t> head{0};  // producer writes
  std::atomic<uint64_t> tail{0};  // consumer reads
  std::atomic<uint64_t> ring_dropped{0};
  std::atomic<uint64_t> late_dropped{0};
  std::atomic<uint64_t> acc_dropped{0};  // node/edge table capacity drops

  // window state (consumer-side only)
  int64_t window_ms;
  int64_t watermark = INT64_MIN;    // max window id seen
  int64_t closed_upto = INT64_MIN;  // windows <= this are emitted, never reopened
  uint32_t max_edges;

  std::vector<WindowAcc*> open;  // open windows, unordered, <= kMaxOpenWindows
  std::vector<WindowAcc*> pool;  // recycled accumulators

  NodeTable nodes;
  // persistent node identity (slots stable across windows)
  std::vector<int32_t> node_uids;
  std::vector<uint8_t> node_types;

  // close_window_feats scratch (consumer-side; persistent so a steady
  // stream of windows allocates nothing)
  std::vector<uint32_t> dst_off;                       // node_count + 1
  // per-node stats interleaved: one 64-byte struct == one cache line
  // per node, so the histogram pass touches 2 lines per edge (src+dst)
  // instead of ~10 across 8 separate arrays. A/B at 110k nodes measured
  // NO difference (the 7 MB accumulator set is L3-resident either way);
  // the interleave is kept for the fleet-scale case where per-node
  // state outgrows L3 and the 8-line pattern would miss on every edge.
  struct alignas(64) NodeAcc {
    double out_cnt, in_cnt, out_err, in_err, out_lat, in_lat, out_deg,
        in_deg;
  };
  static_assert(sizeof(NodeAcc) == 64, "one cache line per node");
  std::vector<NodeAcc> nacc;                           // per-node stats

  // degree-cap scratch (close-path sampling, ISSUE 16): per-edge
  // priorities, a dst-grouped placement order and the survivor flags —
  // persistent like dst_off/nacc so capped closes allocate nothing steady
  // state.
  std::vector<uint64_t> eprio;
  std::vector<uint32_t> eorder;
  std::vector<uint8_t> ekeep;

  Ingest(int64_t wms, uint32_t ring_cap, uint32_t edge_cap, uint32_t node_cap)
      : ring(ring_cap), ring_mask(ring_cap - 1), window_ms(wms),
        max_edges(edge_cap), nodes(node_cap) {}

  ~Ingest() {
    for (WindowAcc* a : open) delete a;
    for (WindowAcc* a : pool) delete a;
  }

  WindowAcc* find_open(int64_t w) {
    for (WindowAcc* a : open) {
      if (a->window_id() == w) return a;
    }
    return nullptr;
  }

  WindowAcc* oldest_open() {
    WindowAcc* best = nullptr;
    for (WindowAcc* a : open) {
      if (best == nullptr || a->window_id() < best->window_id()) best = a;
    }
    return best;
  }

  WindowAcc* acquire(int64_t w) {
    WindowAcc* a;
    if (!pool.empty()) {
      a = pool.back();
      pool.pop_back();
    } else {
      a = new WindowAcc();
    }
    a->open(w);
    open.push_back(a);
    return a;
  }

  void release(WindowAcc* a) {
    for (size_t i = 0; i < open.size(); ++i) {
      if (open[i] == a) {
        open[i] = open.back();
        open.pop_back();
        break;
      }
    }
    pool.push_back(a);
  }
};

inline uint32_t next_pow2(uint32_t v) {
  uint32_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

void accumulate(Ingest* ig, WindowAcc* acc, const AlzRecord& r) {
  int32_t src = ig->nodes.get_or_add(r.from_uid, r.from_type, &ig->node_uids,
                                     &ig->node_types);
  int32_t dst = ig->nodes.get_or_add(r.to_uid, r.to_type, &ig->node_uids,
                                     &ig->node_types);
  if (src < 0 || dst < 0) {  // node table full: drop
    ig->acc_dropped.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  EdgeSlot* e = acc->get_or_add(r.from_uid, r.to_uid, r.protocol, ig->max_edges);
  if (e == nullptr) {  // edge cap reached: drop
    ig->acc_dropped.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  if (e->count == 0) {
    e->src_slot = src;
    e->dst_slot = dst;
  }
  e->count += 1;
  e->lat_sum += r.latency_ns;
  if (r.latency_ns > e->lat_max) e->lat_max = r.latency_ns;
  // err5 matches GraphBuilder: (status >= 500) | !completed — status 0 on a
  // completed request is a success for non-HTTP protocols
  if (r.status >= 500 || (r.flags & 0x2)) e->err5 += 1;
  else if (r.status >= 400) e->err4 += 1;
  if (r.flags & 0x1) e->tls_cnt += 1;
}

}  // namespace

extern "C" {

void* alz_create(int64_t window_ms, uint32_t ring_capacity, uint32_t max_edges,
                 uint32_t max_nodes) {
  return new Ingest(window_ms, next_pow2(ring_capacity), max_edges,
                    next_pow2(max_nodes * 2));
}

void alz_destroy(void* p) { delete static_cast<Ingest*>(p); }

// Producer side: push n records; returns how many were accepted (the rest
// are counted dropped — the l7.go:764-770 drop-not-block contract).
uint32_t alz_push(void* p, const AlzRecord* recs, uint32_t n) {
  Ingest* ig = static_cast<Ingest*>(p);
  uint64_t head = ig->head.load(std::memory_order_relaxed);
  uint64_t tail = ig->tail.load(std::memory_order_acquire);
  uint32_t space = static_cast<uint32_t>(ig->ring.size() - (head - tail));
  uint32_t take = n < space ? n : space;
  for (uint32_t i = 0; i < take; ++i) {
    ig->ring[(head + i) & ig->ring_mask] = recs[i];
  }
  ig->head.store(head + take, std::memory_order_release);
  if (take < n) ig->ring_dropped.fetch_add(n - take, std::memory_order_relaxed);
  return take;
}

// Backpressure drops (ring full) and lateness drops (row for an
// already-emitted window), exported separately so the service gauges do
// not conflate the two failure modes.
uint64_t alz_ring_dropped(void* p) {
  return static_cast<Ingest*>(p)->ring_dropped.load(std::memory_order_relaxed);
}

uint64_t alz_late_dropped(void* p) {
  return static_cast<Ingest*>(p)->late_dropped.load(std::memory_order_relaxed);
}

uint64_t alz_acc_dropped(void* p) {
  return static_cast<Ingest*>(p)->acc_dropped.load(std::memory_order_relaxed);
}

uint64_t alz_dropped(void* p) {  // combined, kept for callers wanting a total
  Ingest* ig = static_cast<Ingest*>(p);
  return ig->ring_dropped.load(std::memory_order_relaxed) +
         ig->late_dropped.load(std::memory_order_relaxed);
}

// Consumer side: drain the ring into per-window accumulators. Returns the
// oldest window id that is ready to close (watermark passed it, like the
// numpy store's `_close_upto(watermark - 1)`), or -2^62.. INT64_MIN when
// nothing is ready. May return ready windows on repeated calls with an
// empty ring — callers loop drain/close until INT64_MIN. If the open-window
// bound is hit, the oldest open window is force-signaled ready and the
// offending record stays in the ring for the next drain.
int64_t alz_drain(void* p) {
  Ingest* ig = static_cast<Ingest*>(p);
  uint64_t tail = ig->tail.load(std::memory_order_relaxed);
  uint64_t head = ig->head.load(std::memory_order_acquire);
  while (tail < head) {
    const AlzRecord& r = ig->ring[tail & ig->ring_mask];
    int64_t w = r.start_time_ms / ig->window_ms;
    if (w <= ig->closed_upto) {
      ig->late_dropped.fetch_add(1, std::memory_order_relaxed);
      ++tail;
      continue;
    }
    WindowAcc* acc = ig->find_open(w);
    if (acc == nullptr) {
      if (ig->open.size() >= kMaxOpenWindows) {
        // out of accumulators: force-close the oldest; record stays queued
        ig->tail.store(tail, std::memory_order_release);
        return ig->oldest_open()->window_id();
      }
      acc = ig->acquire(w);
    }
    accumulate(ig, acc, r);
    if (w > ig->watermark) ig->watermark = w;
    ++tail;
  }
  ig->tail.store(tail, std::memory_order_release);
  WindowAcc* oldest = ig->oldest_open();
  if (oldest != nullptr && oldest->window_id() < ig->watermark) {
    return oldest->window_id();
  }
  return INT64_MIN;
}

// Oldest open window id (the one alz_close_window would close), or
// INT64_MIN when no window is open.
int64_t alz_current_window(void* p) {
  Ingest* ig = static_cast<Ingest*>(p);
  WindowAcc* oldest = ig->oldest_open();
  return oldest == nullptr ? INT64_MIN : oldest->window_id();
}

uint32_t alz_node_count(void* p) {
  return static_cast<uint32_t>(static_cast<Ingest*>(p)->node_uids.size());
}

// Close the oldest open window: export aggregated edges into caller
// buffers (each sized >= max_edges) and mark it emitted. Returns the edge
// count, -1 if buffers are too small, -2 if no window is open. Node tables
// persist across windows; fetch them with alz_export_nodes.
int32_t alz_close_window(void* p, uint32_t buf_cap, int64_t* window_start_ms,
                         int32_t* src, int32_t* dst, uint8_t* protocol,
                         uint64_t* count, uint64_t* lat_sum, uint64_t* lat_max,
                         uint32_t* err5, uint32_t* err4, uint32_t* tls_cnt) {
  Ingest* ig = static_cast<Ingest*>(p);
  WindowAcc* acc = ig->oldest_open();
  if (acc == nullptr) return -2;
  const std::vector<EdgeSlot>& edges = acc->edges();
  if (edges.size() > buf_cap) return -1;
  *window_start_ms = acc->window_id() * ig->window_ms;
  int32_t n = 0;
  for (const EdgeSlot& e : edges) {
    src[n] = e.src_slot;
    dst[n] = e.dst_slot;
    protocol[n] = e.protocol;
    count[n] = e.count;
    lat_sum[n] = e.lat_sum;
    lat_max[n] = e.lat_max;
    err5[n] = e.err5;
    err4[n] = e.err4;
    tls_cnt[n] = e.tls_cnt;
    ++n;
  }
  if (acc->window_id() > ig->closed_upto) ig->closed_upto = acc->window_id();
  ig->release(acc);
  return n;
}

// Edge count of the oldest open window (what close_window would export),
// or -1 when no window is open — lets callers right-size padded buffers
// before the close call.
int64_t alz_current_edge_count(void* p) {
  Ingest* ig = static_cast<Ingest*>(p);
  WindowAcc* oldest = ig->oldest_open();
  return oldest == nullptr ? -1 : static_cast<int64_t>(oldest->edges().size());
}

// Feature-dim contract with graph/builder.py (EDGE_FEATURE_DIM /
// NODE_FEATURE_DIM); the Python binding asserts against these at load.
constexpr uint32_t kEdgeFeatDim = 16;
constexpr uint32_t kNodeFeatDim = 32;
uint32_t alz_edge_feat_dim(void) { return kEdgeFeatDim; }
uint32_t alz_node_feat_dim(void) { return kNodeFeatDim; }

// Close the oldest open window with on-core assembly: edges come out
// **dst-sorted** (counting sort over dense node slots — the layout the
// Pallas scatter kernel requires, snapshot.py:99-114) and both feature
// matrices are computed here in one pass, replacing the numpy
// bincount/log1p/argsort stage that dominated the host path (~120 ms per
// 256k-edge window → ~10 ms). Buffers: src/dst/etype/count sized e_cap;
// ef e_cap*16 floats; nf n_cap*32 floats. ef/nf rows must arrive
// zeroed — only nonzero slots are written (cols 7..15 one-hot, nf cols
// 0..11).
//
// degree_cap > 0 folds alz_sample_degree_cap into the close (ISSUE 16,
// carried ROADMAP item): every over-cap dst keeps the `cap` edges with
// the smallest sample_priorities(seed, window, dst-uid, src-uid, proto)
// — the SAME pure-function draw as graph/builder.py, so serial numpy
// builds and this path select identically. Node features keep the FULL
// pre-cap aggregate (the builder contract: a hot-key dst keeps its real
// in-degree signal); only edge emission is cut. sampled_out[0]/[1]
// report cut edges/rows for the ledger's sampled/degree_cap row.
// Returns the emitted (post-cap) edge count; -1 e_cap too small, -2 no
// open window, -3 n_cap smaller than the node table.
int32_t alz_close_window_feats(void* p, uint32_t e_cap, uint32_t n_cap,
                               int64_t* window_start_ms, float window_s,
                               uint32_t degree_cap, uint64_t sample_seed,
                               int32_t* src, int32_t* dst, int32_t* etype,
                               uint64_t* count, float* ef, float* nf,
                               int64_t* sampled_out) {
  Ingest* ig = static_cast<Ingest*>(p);
  WindowAcc* acc = ig->oldest_open();
  if (acc == nullptr) return -2;
  const std::vector<EdgeSlot>& edges = acc->edges();
  const uint32_t n = static_cast<uint32_t>(edges.size());
  const uint32_t n_nodes = static_cast<uint32_t>(ig->node_uids.size());
  if (n > e_cap) return -1;
  if (n_nodes > n_cap) return -3;
  *window_start_ms = acc->window_id() * ig->window_ms;
  sampled_out[0] = 0;
  sampled_out[1] = 0;

  ig->dst_off.assign(n_nodes + 1, 0);
  ig->nacc.assign(n_nodes, Ingest::NodeAcc{});
  Ingest::NodeAcc* nacc = ig->nacc.data();

  // pass 1: dst histogram + per-node accumulators (2 cache lines/edge).
  // Runs over ALL edges — node features see the pre-cap aggregate.
  uint32_t max_in_deg = 0;
  for (const EdgeSlot& e : edges) {
    const uint32_t deg = ++ig->dst_off[e.dst_slot + 1];
    if (deg > max_in_deg) max_in_deg = deg;
    const double c = static_cast<double>(e.count);
    Ingest::NodeAcc& s = nacc[e.src_slot];
    Ingest::NodeAcc& d = nacc[e.dst_slot];
    s.out_cnt += c;
    d.in_cnt += c;
    s.out_err += e.err5;
    d.in_err += e.err5;
    s.out_lat += static_cast<double>(e.lat_sum);
    d.in_lat += static_cast<double>(e.lat_sum);
    s.out_deg += 1.0;
    d.in_deg += 1.0;
  }
  for (uint32_t i = 0; i < n_nodes; ++i) ig->dst_off[i + 1] += ig->dst_off[i];

  // cap pass: bottom-k per over-cap dst by (priority, arena index). The
  // priority replicates graph/builder.py sample_priorities bit-for-bit:
  // base = mix64((seed << 32) ^ window_start_ms); per edge
  // mix64((u64(i64(dst_uid)) << 32) ^ u64(i64(src_uid)) ^ (proto << 56)
  // ^ base) — sign-extended uids, exactly the numpy int64→uint64 casts.
  uint32_t n_emit = n;
  const bool capped = degree_cap > 0 && max_in_deg > degree_cap;
  if (capped) {
    const uint64_t base =
        mix64((sample_seed << 32) ^ static_cast<uint64_t>(*window_start_ms));
    ig->eprio.resize(n);
    ig->eorder.resize(n);
    ig->ekeep.assign(n, 1);
    // dst-grouped placement (same counting sort as pass 2, on a copy of
    // the offsets) so each dst's edges are a contiguous slice of eorder
    std::vector<uint32_t> place(ig->dst_off.begin(), ig->dst_off.end() - 1);
    for (uint32_t i = 0; i < n; ++i) {
      const EdgeSlot& e = edges[i];
      uint64_t x =
          (static_cast<uint64_t>(static_cast<int64_t>(e.to_uid)) << 32) ^
          static_cast<uint64_t>(static_cast<int64_t>(e.from_uid)) ^
          (static_cast<uint64_t>(e.protocol) << 56);
      ig->eprio[i] = mix64(x ^ base);
      ig->eorder[place[e.dst_slot]++] = i;
    }
    const uint64_t* prio = ig->eprio.data();
    for (uint32_t g = 0; g < n_nodes; ++g) {
      // after the prefix sum, dst slot g's edges span
      // [dst_off[g], dst_off[g+1]) of the placement order
      const uint32_t g0 = ig->dst_off[g];
      const uint32_t g1 = ig->dst_off[g + 1];
      const uint32_t size = g1 - g0;
      if (size <= degree_cap) continue;
      uint32_t* beg = ig->eorder.data() + g0;
      uint32_t* end = ig->eorder.data() + g1;
      std::nth_element(beg, beg + degree_cap, end,
                       [prio](uint32_t a, uint32_t b) {
                         return prio[a] != prio[b] ? prio[a] < prio[b] : a < b;
                       });
      for (uint32_t* it = beg + degree_cap; it != end; ++it) {
        ig->ekeep[*it] = 0;
        sampled_out[0] += 1;
        sampled_out[1] += static_cast<int64_t>(edges[*it].count);
      }
    }
    n_emit = n - static_cast<uint32_t>(sampled_out[0]);
    // rebuild the dst histogram over the SURVIVORS for pass 2 placement
    ig->dst_off.assign(n_nodes + 1, 0);
    for (uint32_t i = 0; i < n; ++i) {
      if (ig->ekeep[i]) ig->dst_off[edges[i].dst_slot + 1] += 1;
    }
    for (uint32_t i = 0; i < n_nodes; ++i) ig->dst_off[i + 1] += ig->dst_off[i];
  }

  // pass 2: place each edge at its sorted position, features inline
  const double ws = window_s > 1e-6f ? static_cast<double>(window_s) : 1e-6;
  for (uint32_t i = 0; i < n; ++i) {
    const EdgeSlot& e = edges[i];
    if (capped && !ig->ekeep[i]) continue;
    const uint32_t pos = ig->dst_off[e.dst_slot]++;
    src[pos] = e.src_slot;
    dst[pos] = e.dst_slot;
    etype[pos] = e.protocol;
    count[pos] = e.count;
    float* f = ef + static_cast<size_t>(pos) * kEdgeFeatDim;
    const double c = static_cast<double>(e.count);
    const double cdiv = c > 1.0 ? c : 1.0;
    f[0] = static_cast<float>(std::log1p(c));
    f[1] = static_cast<float>(std::log1p(static_cast<double>(e.lat_sum) / cdiv) / 20.0);
    f[2] = static_cast<float>(std::log1p(static_cast<double>(e.lat_max)) / 20.0);
    f[3] = static_cast<float>(e.err5 / cdiv);
    f[4] = static_cast<float>(e.err4 / cdiv);
    f[5] = static_cast<float>(e.tls_cnt / cdiv);
    f[6] = static_cast<float>(std::log1p(c / ws));
    const uint32_t proto =
        e.protocol >= kProtoCount ? kProtoCount - 1 : e.protocol;
    f[7 + proto] = 1.0f;
  }

  // node features (cols 0..11; 12+ stay zero for k8s enrichment)
  for (uint32_t i = 0; i < n_nodes; ++i) {
    float* f = nf + static_cast<size_t>(i) * kNodeFeatDim;
    const uint8_t t = ig->node_types[i];
    if (t < 4) f[t] = 1.0f;
    const Ingest::NodeAcc& a = nacc[i];
    const double oc = a.out_cnt > 1.0 ? a.out_cnt : 1.0;
    const double ic = a.in_cnt > 1.0 ? a.in_cnt : 1.0;
    f[4] = static_cast<float>(std::log1p(a.out_cnt));
    f[5] = static_cast<float>(std::log1p(a.in_cnt));
    f[6] = static_cast<float>(a.out_err / oc);
    f[7] = static_cast<float>(a.in_err / ic);
    f[8] = static_cast<float>(std::log1p(a.out_lat / oc) / 20.0);
    f[9] = static_cast<float>(std::log1p(a.in_lat / ic) / 20.0);
    f[10] = static_cast<float>(std::log1p(a.out_deg));
    f[11] = static_cast<float>(std::log1p(a.in_deg));
  }

  if (acc->window_id() > ig->closed_upto) ig->closed_upto = acc->window_id();
  ig->release(acc);
  return static_cast<int32_t>(n_emit);
}

// ---------------------------------------------------------------------------
// Generic grouped reduction over packed int64 keys — the numpy builder's
// per-window argsort+reduceat grouping stage, moved on-core (ROADMAP
// "Ingest follow-ups"; graph/builder.py group_reduce routes here when the
// .so is loaded, with the numpy path kept as the fallback). STATELESS on
// purpose: no Ingest handle, no shared scratch — the sharded ingest
// pipeline calls it concurrently from every shard worker for the
// per-window partial aggregation AND from the merge stage for the
// per-edge-key recombine.
//
// Inputs: keys[n]; n_sum double columns to per-group SUM; n_max double
// columns to per-group MAX. Outputs (caller buffers, each sized out_cap
// >= the group count — n always suffices): ascending unique keys (the
// exact group order np.argsort produces), per-group row counts, a
// representative row index per group (first-seen), and the reduced
// columns. Sums are order-sensitive only for non-integer-valued doubles;
// every column the builder feeds is integer-valued, so results are
// bit-identical to the numpy reduceat path. Returns the group count, or
// -1 when out_cap is too small.
int64_t alz_group_edges(const int64_t* keys, uint64_t n,
                        const double* const* sum_cols, uint32_t n_sum,
                        const double* const* max_cols, uint32_t n_max,
                        uint64_t out_cap, int64_t* out_keys, double* out_count,
                        int64_t* out_rep, double* const* out_sums,
                        double* const* out_maxes) {
  if (n == 0) return 0;
  // group ids live in uint32 — refuse inputs past 2^31 rows (window
  // scale is orders of magnitude below; callers treat <0 as "use the
  // numpy fallback", so the bound degrades gracefully, never hangs)
  if (n > (1ull << 31)) return -1;
  // Pass 1: open-addressing probe assigns a dense group id per distinct
  // key and a per-row group index — O(n), no sort of the row stream.
  // Pass 2 ranks the E distinct keys ascending (E log E over groups
  // only) and accumulates every reduction straight into the caller's
  // output buffers through the rank remap. The working set is
  // E-proportional (the aggregated edge list), not n-proportional — the
  // reason this beats sorting the full row stream at service-map
  // compression ratios.
  uint64_t cap = 64;
  while (cap < 2 * n) cap <<= 1;
  const uint64_t mask = cap - 1;
  std::vector<uint32_t> index(cap, UINT32_MAX);
  std::vector<int64_t> gkeys;
  std::vector<int64_t> grep;
  gkeys.reserve(1024);
  grep.reserve(1024);
  std::vector<uint32_t> ginv(n);
  for (uint64_t i = 0; i < n; ++i) {
    const int64_t key = keys[i];
    uint64_t h = mix64(static_cast<uint64_t>(key));
    for (;; ++h) {
      uint32_t& slot = index[h & mask];
      if (slot == UINT32_MAX) {
        slot = static_cast<uint32_t>(gkeys.size());
        ginv[i] = slot;
        gkeys.push_back(key);
        grep.push_back(static_cast<int64_t>(i));
        break;
      }
      if (gkeys[slot] == key) {
        ginv[i] = slot;
        break;
      }
    }
  }
  const uint64_t n_groups = gkeys.size();
  if (n_groups > out_cap) return -1;

  // rank groups by ascending key — the group order the numpy path's
  // argsort produces, which is also the dst-major order the batcher needs
  std::vector<uint32_t> order(n_groups);
  for (uint32_t g = 0; g < n_groups; ++g) order[g] = g;
  std::sort(order.begin(), order.end(),
            [&gkeys](uint32_t x, uint32_t y) { return gkeys[x] < gkeys[y]; });
  std::vector<uint32_t> rank(n_groups);
  for (uint32_t o = 0; o < n_groups; ++o) {
    const uint32_t g = order[o];
    rank[g] = o;
    out_keys[o] = gkeys[g];
    out_rep[o] = grep[g];
    out_count[o] = 0.0;
  }
  for (uint32_t c = 0; c < n_sum; ++c)
    std::memset(out_sums[c], 0, n_groups * sizeof(double));

  // pass 2: accumulate into the ranked outputs (E-sized, cache-warm)
  for (uint64_t i = 0; i < n; ++i) {
    const uint32_t o = rank[ginv[i]];
    out_count[o] += 1.0;
    for (uint32_t c = 0; c < n_sum; ++c) out_sums[c][o] += sum_cols[c][i];
    for (uint32_t c = 0; c < n_max; ++c) {
      const double v = max_cols[c][i];
      double& m = out_maxes[c][o];
      if (out_count[o] == 1.0 || v > m) m = v;
    }
  }
  return static_cast<int64_t>(n_groups);
}

// ---------------------------------------------------------------------------
// Degree-capped neighbor sampling (ISSUE 7). Operates over the
// dst-grouped aggregated edge list the grouping stage emits (dst[] is
// dst-sorted — ascending dst-major group keys, alz_group_edges'
// contract): for every dst whose in-degree exceeds `cap`, keep the
// `cap` edges with the SMALLEST priority (bottom-k — the deterministic
// form of reservoir sampling: with hash-random priorities, bottom-k is
// a uniform sample, and the same (seed, window, dst-uid, src-uid) keys
// always draw the same sample, so N-worker merges and reruns select
// identically). Priorities are computed caller-side (one shared
// definition, graph/builder.py sample_priorities, mix64 over the uid
// pair) so the C++ path and the numpy fallback can never hash apart.
//
// STATELESS like alz_group_edges — the sharded merge calls it on the
// merge thread, parity tests call it concurrently. Selection ties
// break by ascending row index, matching numpy's stable lexsort, so
// both backends are bit-identical. Kept indices are written ascending
// (the dst-major order of the input survives the cut). Returns the
// kept count; -1 when out_cap is too small (never with out_cap == n),
// -2 on cap == 0 (unlimited is the CALLER's fast path, not a mode
// here).
int64_t alz_sample_degree_cap(const int32_t* dst, const uint64_t* prio,
                              int64_t n, uint32_t cap, int64_t* out_idx,
                              uint64_t out_cap) {
  if (cap == 0) return -2;
  int64_t kept = 0;
  std::vector<int64_t> heavy;  // per-group scratch, reused across groups
  int64_t g0 = 0;
  while (g0 < n) {
    const int32_t d = dst[g0];
    int64_t g1 = g0 + 1;
    while (g1 < n && dst[g1] == d) ++g1;
    const int64_t size = g1 - g0;
    if (size <= static_cast<int64_t>(cap)) {
      if (kept + size > static_cast<int64_t>(out_cap)) return -1;
      for (int64_t i = g0; i < g1; ++i) out_idx[kept++] = i;
    } else {
      heavy.resize(static_cast<size_t>(size));
      for (int64_t i = 0; i < size; ++i) heavy[static_cast<size_t>(i)] = g0 + i;
      // O(size) partial selection of the cap smallest (prio, idx) pairs
      std::nth_element(
          heavy.begin(), heavy.begin() + cap, heavy.end(),
          [prio](int64_t a, int64_t b) {
            return prio[a] != prio[b] ? prio[a] < prio[b] : a < b;
          });
      std::sort(heavy.begin(), heavy.begin() + cap);  // restore dst-major order
      if (kept + static_cast<int64_t>(cap) > static_cast<int64_t>(out_cap))
        return -1;
      for (uint32_t i = 0; i < cap; ++i) out_idx[kept++] = heavy[i];
    }
    g0 = g1;
  }
  return kept;
}

// ---------------------------------------------------------------------------
// Native batch L7 engine (ISSUE 16): the `_process_l7_inner` join +
// attribution + REQUEST-row emission body in one pass over the batch.
// STATELESS like alz_group_edges — every piece of mutable state stays
// Python-owned and arrives as arrays:
//
//  - the socket-line table comes in FLATTENED (per-line entry slices of
//    one concatenated arena, lines lexsorted by (pid, fd), offsets
//    sl_off[n_lines+1]) — a snapshot the binding caches and rebuilds only
//    when the store's revision counter moves;
//  - pod/service attribution tables are the _IpTable._compile() arrays
//    (sorted u32 ips / i32 uids — recompiles swap arrays, never mutate,
//    so handing them over without a lock is safe);
//  - emitted REQUEST rows land in `out` in ORIGINAL row order (the order
//    the numpy boolean-mask path preserves), with kept_idx/unmatched_idx
//    reporting ascending original indexes so the Python side can requeue
//    retry rows and keep DropLedger `filtered` accounting EXACT:
//    counts[0] = unmatched (no_socket/requeue), counts[1] = not_pod.
//
// The caller holds the GIL only to hand these blocks off — ctypes
// releases it for the call, so thread-mode shards overlap here too.
// Stateful corners stay Python (the backend's documented refusal
// surface): retry scheduling, outbound reverse-DNS interning, payload
// path enrichment, h2/kafka reassembly, proc/k8s folds, rate limiting.
// ---------------------------------------------------------------------------

// _IpTable.lookup for one ip: searchsorted(side=left), clip to size-1,
// exact-match test; uid 0 on miss (the np.where(found, uids, 0) contract)
static int32_t alz_ip_lookup_(const uint32_t* ips, const int32_t* uids,
                              int64_t n, uint32_t ip, bool* found) {
  if (n == 0) {
    *found = false;
    return 0;
  }
  int64_t idx = std::lower_bound(ips, ips + n, ip) - ips;
  if (idx >= n) idx = n - 1;
  *found = ips[idx] == ip;
  return *found ? uids[idx] : 0;
}

// Open-addressed exact-match mirror of alz_ip_lookup_ for the batch hot
// loop: the compiled tables are consulted 2-3x PER ROW, and a dependent-
// load binary search chain costs ~10 mispredict-prone probes per lookup
// where one L1-resident probe suffices. Built per call (the tables are
// snapshots that never mutate in place) when the batch is large enough
// to amortize the inserts — a pure access-path swap, the (found, uid)
// result for every ip is identical to the binary search by construction.
struct AlzIpHash {
  std::vector<uint32_t> key;
  std::vector<int32_t> uid;
  std::vector<uint8_t> used;
  uint32_t mask = 0;

  void build(const uint32_t* ips, const int32_t* uids, int64_t n) {
    uint32_t cap = 16;
    while (cap < static_cast<uint64_t>(n) * 2) cap <<= 1;
    mask = cap - 1;
    key.assign(cap, 0);
    uid.assign(cap, 0);
    used.assign(cap, 0);
    for (int64_t i = 0; i < n; ++i) {
      uint32_t slot = (ips[i] * 0x9E3779B9u) & mask;
      while (used[slot]) slot = (slot + 1) & mask;  // keys are unique
      key[slot] = ips[i];
      uid[slot] = uids[i];
      used[slot] = 1;
    }
  }

  int32_t lookup(uint32_t ip, bool* found) const {
    uint32_t slot = (ip * 0x9E3779B9u) & mask;
    while (used[slot]) {
      if (key[slot] == ip) {
        *found = true;
        return uid[slot];
      }
      slot = (slot + 1) & mask;
    }
    *found = false;
    return 0;
  }
};

// SocketLine.get_values (sockline.py) case-for-case for ONE timestamp
// over flattened entries [a, b); uint64 subtractions wrap exactly like
// the numpy side's. Returns the selected LOCAL entry index, or -1.
static int64_t alz_sockline_pick_(const uint64_t* ts, const uint8_t* open_,
                                  const uint32_t* daddr, const uint16_t* dport,
                                  int64_t a, int64_t b, uint64_t t) {
  const int64_t nL = b - a;
  if (nL == 0) return -1;
  const uint64_t* base = ts + a;
  const int64_t idx = std::lower_bound(base, ts + b, t) - base;  // side="left"
  if (idx == nL) {  // after the last entry
    if (open_[b - 1]) return nL - 1;
    if (nL >= 2 && open_[b - 2] && (t - ts[b - 2]) < 60000000000ULL)
      return nL - 2;  // ONE_MINUTE_NS close-race tolerance
    return -1;
  }
  if (idx == 0) return open_[a] ? 0 : -1;  // before the first entry
  const int64_t prev = idx - 1;
  if (open_[a + prev]) return prev;
  // landed on a close: neighbor-agreement heuristic
  const int64_t cp = prev - 1;
  const int64_t ca = prev + 1;  // == idx, < nL in this branch
  if (cp < 0 || !open_[a + cp] || !open_[a + ca]) return -1;
  if (daddr[a + cp] != daddr[a + ca] || dport[a + cp] != dport[a + ca])
    return -1;
  return (t - ts[a + cp]) < (ts[a + ca] - t) ? cp : ca;
}

int64_t alz_process_l7(const AlzL7Event* ev, int64_t n, uint64_t now_ns,
                       const uint32_t* sl_pid, const uint64_t* sl_fd,
                       const int64_t* sl_off, int64_t n_lines,
                       const uint64_t* sl_ts, const uint8_t* sl_open,
                       const uint32_t* sl_saddr, const uint16_t* sl_sport,
                       const uint32_t* sl_daddr, const uint16_t* sl_dport,
                       uint8_t* sl_touched, const uint32_t* pod_ips,
                       const int32_t* pod_uids, int64_t n_pod,
                       const uint32_t* svc_ips, const int32_t* svc_uids,
                       int64_t n_svc, AlzRequest* out, int64_t* kept_idx,
                       int64_t* unmatched_idx, int64_t* counts) {
  (void)now_ns;  // _last_match writeback happens Python-side via sl_touched
  counts[0] = 0;
  counts[1] = 0;
  if (n <= 0) return 0;

  // -- phase 1: V1 socket-line join for rows without embedded addresses.
  // `matched` exists only when the batch HAS V1 rows — the all-V2 hot
  // path (every row carries addresses) skips the flag vector entirely
  // and phase 2 runs branch-free on it.
  std::vector<uint8_t> matched;
  std::vector<uint32_t> jsa, jda;
  std::vector<uint16_t> jsp, jdp;
  std::vector<std::pair<uint64_t, int64_t>> keyed;
  for (int64_t i = 0; i < n; ++i) {
    if (ev[i].daddr == 0) {
      // the SAME hashed conn key the numpy path groups on — collisions
      // fold (pid, fd) pairs together there, so they must fold here too
      const uint64_t key = (static_cast<uint64_t>(ev[i].pid) << 32) ^
                           (ev[i].fd * 0x9E3779B97F4A7C15ULL);
      keyed.emplace_back(key, i);
    }
  }
  const bool any_v1 = !keyed.empty();
  if (any_v1) {
    matched.assign(static_cast<size_t>(n), 1);
    for (const auto& k : keyed) matched[static_cast<size_t>(k.second)] = 0;
    jsa.resize(static_cast<size_t>(n));
    jsp.resize(static_cast<size_t>(n));
    jda.resize(static_cast<size_t>(n));
    jdp.resize(static_cast<size_t>(n));
    // stable: rows inside a key group stay in original order, so the
    // group head is the first occurrence — numpy's sel[0]
    std::stable_sort(
        keyed.begin(), keyed.end(),
        [](const std::pair<uint64_t, int64_t>& x,
           const std::pair<uint64_t, int64_t>& y) { return x.first < y.first; });
    size_t g0 = 0;
    while (g0 < keyed.size()) {
      size_t g1 = g0 + 1;
      while (g1 < keyed.size() && keyed[g1].first == keyed[g0].first) ++g1;
      const AlzL7Event& head = ev[keyed[g0].second];
      // binary search the (pid, fd) pair in the lexsorted snapshot keys
      int64_t lo = 0, hi = n_lines;
      while (lo < hi) {
        const int64_t mid = lo + (hi - lo) / 2;
        if (sl_pid[mid] < head.pid ||
            (sl_pid[mid] == head.pid && sl_fd[mid] < head.fd)) {
          lo = mid + 1;
        } else {
          hi = mid;
        }
      }
      if (lo < n_lines && sl_pid[lo] == head.pid && sl_fd[lo] == head.fd) {
        const int64_t a = sl_off[lo];
        const int64_t b = sl_off[lo + 1];
        for (size_t k = g0; k < g1; ++k) {
          const int64_t row = keyed[k].second;
          const int64_t sel = alz_sockline_pick_(sl_ts, sl_open, sl_daddr,
                                                 sl_dport, a, b,
                                                 ev[row].write_time_ns);
          if (sel < 0) continue;
          jsa[static_cast<size_t>(row)] = sl_saddr[a + sel];
          jsp[static_cast<size_t>(row)] = sl_sport[a + sel];
          jda[static_cast<size_t>(row)] = sl_daddr[a + sel];
          jdp[static_cast<size_t>(row)] = sl_dport[a + sel];
          matched[static_cast<size_t>(row)] = 1;
          sl_touched[a + sel] = 1;
        }
      }
      g0 = g1;
    }
  }

  // -- phase 2: sequential original-order pass — requeue partition,
  // pod/service attribution, REQUEST row fill (the numpy boolean-mask
  // order is ascending original index, reproduced exactly). Attribution
  // goes through the L1-resident hash mirrors when the batch is large
  // enough to amortize building them (2-3 lookups per row; identical
  // (found, uid) results either way), and the service probe is skipped
  // when the destination already matched a pod — the to_type chain
  // never consults it in that case.
  const bool use_hash = n >= 64 && n >= (n_pod + n_svc) / 4;
  AlzIpHash pod_h, svc_h;
  if (use_hash) {
    pod_h.build(pod_ips, pod_uids, n_pod);
    svc_h.build(svc_ips, svc_uids, n_svc);
  }
  int64_t n_emit = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (i + 4 < n) {
      // the 331-byte rows defeat the adjacent-line prefetcher; pull the
      // row 4 ahead while this one's lookups resolve
      __builtin_prefetch(ev + i + 4);
    }
    if (any_v1 && !matched[static_cast<size_t>(i)]) {
      unmatched_idx[counts[0]++] = i;
      continue;
    }
    const AlzL7Event& e = ev[i];
    const bool via_join = e.daddr == 0;
    const uint32_t sa = via_join ? jsa[static_cast<size_t>(i)] : e.saddr;
    const uint16_t sp = via_join ? jsp[static_cast<size_t>(i)] : e.sport;
    const uint32_t da = via_join ? jda[static_cast<size_t>(i)] : e.daddr;
    const uint16_t dp = via_join ? jdp[static_cast<size_t>(i)] : e.dport;
    bool from_pod = false;
    const int32_t from_uid =
        use_hash ? pod_h.lookup(sa, &from_pod)
                 : alz_ip_lookup_(pod_ips, pod_uids, n_pod, sa, &from_pod);
    if (!from_pod) {  // From must be a pod (setFromToV2 contract)
      counts[1] += 1;
      continue;
    }
    bool to_pod = false, to_svc = false;
    const int32_t to_pod_uid =
        use_hash ? pod_h.lookup(da, &to_pod)
                 : alz_ip_lookup_(pod_ips, pod_uids, n_pod, da, &to_pod);
    const int32_t to_svc_uid =
        to_pod ? 0
               : (use_hash
                      ? svc_h.lookup(da, &to_svc)
                      : alz_ip_lookup_(svc_ips, svc_uids, n_svc, da, &to_svc));
    AlzRequest& r = out[n_emit];
    r.start_time_ms = static_cast<int64_t>(e.write_time_ns / 1000000ULL);
    r.latency_ns = e.duration_ns;
    r.from_ip = sa;
    r.from_type = 1;  // EP_POD
    r.from_uid = from_uid;
    r.from_port = sp;
    r.to_ip = da;
    r.to_type = to_pod ? 1 : (to_svc ? 2 : 3);  // EP_POD/EP_SERVICE/EP_OUTBOUND
    r.to_uid = to_pod ? to_pod_uid : (to_svc ? to_svc_uid : 0);
    r.to_port = dp;
    r.protocol = e.protocol;
    r.tls = e.tls;
    r.completed = 1;
    r.status_code = e.status;
    r.fail_reason = 0;
    r.method = e.method;
    r.path = 0;
    kept_idx[n_emit] = i;
    ++n_emit;
  }
  return n_emit;
}

uint32_t alz_export_nodes(void* p, uint32_t buf_cap, int32_t* uids, uint8_t* types) {
  Ingest* ig = static_cast<Ingest*>(p);
  uint32_t n = static_cast<uint32_t>(ig->node_uids.size());
  if (n > buf_cap) n = buf_cap;
  std::memcpy(uids, ig->node_uids.data(), n * sizeof(int32_t));
  std::memcpy(types, ig->node_types.data(), n * sizeof(uint8_t));
  return n;
}

// ---------------------------------------------------------------------------
// ABI self-description (alazspec ALZ020/ALZ022). The loaded .so reports
// the layout it was COMPILED with — offsetof/sizeof truth, not parser
// output — so graph/native.py can refuse a drifted binary at load and
// tools/alazspec can triangulate source ↔ binary ↔ numpy dtype.
// Format: "AlzRecord:<sizeof>;<field>:<offset>:<size>;..." — mirrored by
// events/schema.py dtype_layout() on the Python side.
// ---------------------------------------------------------------------------

const char* alz_abi_record_layout(void) {
  static const std::string layout = [] {
    char buf[512];
    std::snprintf(
        buf, sizeof(buf),
        "AlzRecord:%zu;"
        "start_time_ms:%zu:%zu;latency_ns:%zu:%zu;from_uid:%zu:%zu;"
        "to_uid:%zu:%zu;status:%zu:%zu;from_type:%zu:%zu;"
        "to_type:%zu:%zu;protocol:%zu:%zu;flags:%zu:%zu",
        sizeof(AlzRecord),
        offsetof(AlzRecord, start_time_ms), sizeof(AlzRecord::start_time_ms),
        offsetof(AlzRecord, latency_ns), sizeof(AlzRecord::latency_ns),
        offsetof(AlzRecord, from_uid), sizeof(AlzRecord::from_uid),
        offsetof(AlzRecord, to_uid), sizeof(AlzRecord::to_uid),
        offsetof(AlzRecord, status), sizeof(AlzRecord::status),
        offsetof(AlzRecord, from_type), sizeof(AlzRecord::from_type),
        offsetof(AlzRecord, to_type), sizeof(AlzRecord::to_type),
        offsetof(AlzRecord, protocol), sizeof(AlzRecord::protocol),
        offsetof(AlzRecord, flags), sizeof(AlzRecord::flags));
    return std::string(buf);
  }();
  return layout.c_str();
}

// L7 engine wire mirrors, same offsetof/sizeof self-description: the
// binding refuses to route process_l7 through a .so whose compiled
// layouts disagree with L7_EVENT_DTYPE / REQUEST_DTYPE.
const char* alz_abi_l7_event_layout(void) {
  static const std::string layout = [] {
    char buf[1024];
    std::snprintf(
        buf, sizeof(buf),
        "AlzL7Event:%zu;"
        "pid:%zu:%zu;fd:%zu:%zu;write_time_ns:%zu:%zu;duration_ns:%zu:%zu;"
        "protocol:%zu:%zu;method:%zu:%zu;tls:%zu:%zu;failed:%zu:%zu;"
        "status:%zu:%zu;payload_size:%zu:%zu;payload_read_complete:%zu:%zu;"
        "tid:%zu:%zu;seq:%zu:%zu;kafka_api_version:%zu:%zu;"
        "mysql_prep_stmt_id:%zu:%zu;saddr:%zu:%zu;sport:%zu:%zu;"
        "daddr:%zu:%zu;dport:%zu:%zu;event_read_time_ns:%zu:%zu;"
        "payload:%zu:%zu",
        sizeof(AlzL7Event),
        offsetof(AlzL7Event, pid), sizeof(AlzL7Event::pid),
        offsetof(AlzL7Event, fd), sizeof(AlzL7Event::fd),
        offsetof(AlzL7Event, write_time_ns), sizeof(AlzL7Event::write_time_ns),
        offsetof(AlzL7Event, duration_ns), sizeof(AlzL7Event::duration_ns),
        offsetof(AlzL7Event, protocol), sizeof(AlzL7Event::protocol),
        offsetof(AlzL7Event, method), sizeof(AlzL7Event::method),
        offsetof(AlzL7Event, tls), sizeof(AlzL7Event::tls),
        offsetof(AlzL7Event, failed), sizeof(AlzL7Event::failed),
        offsetof(AlzL7Event, status), sizeof(AlzL7Event::status),
        offsetof(AlzL7Event, payload_size), sizeof(AlzL7Event::payload_size),
        offsetof(AlzL7Event, payload_read_complete),
        sizeof(AlzL7Event::payload_read_complete),
        offsetof(AlzL7Event, tid), sizeof(AlzL7Event::tid),
        offsetof(AlzL7Event, seq), sizeof(AlzL7Event::seq),
        offsetof(AlzL7Event, kafka_api_version),
        sizeof(AlzL7Event::kafka_api_version),
        offsetof(AlzL7Event, mysql_prep_stmt_id),
        sizeof(AlzL7Event::mysql_prep_stmt_id),
        offsetof(AlzL7Event, saddr), sizeof(AlzL7Event::saddr),
        offsetof(AlzL7Event, sport), sizeof(AlzL7Event::sport),
        offsetof(AlzL7Event, daddr), sizeof(AlzL7Event::daddr),
        offsetof(AlzL7Event, dport), sizeof(AlzL7Event::dport),
        offsetof(AlzL7Event, event_read_time_ns),
        sizeof(AlzL7Event::event_read_time_ns),
        offsetof(AlzL7Event, payload), sizeof(AlzL7Event::payload));
    return std::string(buf);
  }();
  return layout.c_str();
}

const char* alz_abi_request_layout(void) {
  static const std::string layout = [] {
    char buf[1024];
    std::snprintf(
        buf, sizeof(buf),
        "AlzRequest:%zu;"
        "start_time_ms:%zu:%zu;latency_ns:%zu:%zu;from_ip:%zu:%zu;"
        "from_type:%zu:%zu;from_uid:%zu:%zu;from_port:%zu:%zu;"
        "to_ip:%zu:%zu;to_type:%zu:%zu;to_uid:%zu:%zu;to_port:%zu:%zu;"
        "protocol:%zu:%zu;tls:%zu:%zu;completed:%zu:%zu;"
        "status_code:%zu:%zu;fail_reason:%zu:%zu;method:%zu:%zu;path:%zu:%zu",
        sizeof(AlzRequest),
        offsetof(AlzRequest, start_time_ms), sizeof(AlzRequest::start_time_ms),
        offsetof(AlzRequest, latency_ns), sizeof(AlzRequest::latency_ns),
        offsetof(AlzRequest, from_ip), sizeof(AlzRequest::from_ip),
        offsetof(AlzRequest, from_type), sizeof(AlzRequest::from_type),
        offsetof(AlzRequest, from_uid), sizeof(AlzRequest::from_uid),
        offsetof(AlzRequest, from_port), sizeof(AlzRequest::from_port),
        offsetof(AlzRequest, to_ip), sizeof(AlzRequest::to_ip),
        offsetof(AlzRequest, to_type), sizeof(AlzRequest::to_type),
        offsetof(AlzRequest, to_uid), sizeof(AlzRequest::to_uid),
        offsetof(AlzRequest, to_port), sizeof(AlzRequest::to_port),
        offsetof(AlzRequest, protocol), sizeof(AlzRequest::protocol),
        offsetof(AlzRequest, tls), sizeof(AlzRequest::tls),
        offsetof(AlzRequest, completed), sizeof(AlzRequest::completed),
        offsetof(AlzRequest, status_code), sizeof(AlzRequest::status_code),
        offsetof(AlzRequest, fail_reason), sizeof(AlzRequest::fail_reason),
        offsetof(AlzRequest, method), sizeof(AlzRequest::method),
        offsetof(AlzRequest, path), sizeof(AlzRequest::path));
    return std::string(buf);
  }();
  return layout.c_str();
}

// sha256 prefix of the ingest.cc this binary was compiled from (the
// Makefile stamp); "unstamped" for out-of-band builds.
const char* alz_source_hash(void) { return ALZ_SOURCE_HASH; }

}  // extern "C"
