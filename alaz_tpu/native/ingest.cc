// Host ingest plane: lock-free ring buffer + windowed edge accumulator.
//
// This is the native core of the graph batcher (SURVEY §2.1 "TPU-native
// equivalents": the C++ analog of the reference's kernel-side event plane,
// playing the role l7.c's maps play — bounded, drop-not-block, fixed-size
// records). Producers push resolved edge records into a SPSC ring; the
// consumer drains into an open-addressing accumulator keyed
// (from_uid, to_uid, protocol) per time window; closed windows export COO
// arrays + per-node tables directly into caller-provided (numpy) buffers.
//
// Build: make -C alaz_tpu/native   → libalaz_ingest.so (ctypes-loaded by
// alaz_tpu/graph/native.py; the pure-numpy GraphBuilder is the fallback).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// 32-byte wire record; mirrored by NATIVE_RECORD_DTYPE in graph/native.py.
// flags: bit0 = tls, bit1 = failed (request not completed)
struct AlzRecord {
  int64_t start_time_ms;
  uint64_t latency_ns;
  int32_t from_uid;
  int32_t to_uid;
  uint32_t status;
  uint8_t from_type;
  uint8_t to_type;
  uint8_t protocol;
  uint8_t flags;
};

struct EdgeSlot {
  int32_t from_uid;
  int32_t to_uid;
  uint8_t protocol;
  uint8_t used;
  int32_t src_slot;
  int32_t dst_slot;
  uint64_t count;
  uint64_t lat_sum;
  uint64_t lat_max;
  uint32_t err5;
  uint32_t err4;
  uint32_t tls_cnt;
};

struct NodeSlot {
  int32_t uid;
  int32_t slot;  // dense node index
  uint8_t type;
  uint8_t used;
};

}  // extern "C"

namespace {

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

class NodeTable {
 public:
  explicit NodeTable(uint32_t cap_pow2) : mask_(cap_pow2 - 1), slots_(cap_pow2) {}

  // uid -> dense slot (insert on miss); -1 when full
  int32_t get_or_add(int32_t uid, uint8_t type, std::vector<int32_t>* uids,
                     std::vector<uint8_t>* types) {
    uint64_t h = mix64(static_cast<uint64_t>(static_cast<uint32_t>(uid)));
    for (uint32_t probe = 0; probe <= mask_; ++probe) {
      NodeSlot& s = slots_[(h + probe) & mask_];
      if (!s.used) {
        s.used = 1;
        s.uid = uid;
        s.type = type;
        s.slot = static_cast<int32_t>(uids->size());
        uids->push_back(uid);
        types->push_back(type);
        return s.slot;
      }
      if (s.uid == uid) return s.slot;
    }
    return -1;
  }

 private:
  uint32_t mask_;
  std::vector<NodeSlot> slots_;
};

class EdgeTable {
 public:
  explicit EdgeTable(uint32_t cap_pow2) : mask_(cap_pow2 - 1), slots_(cap_pow2) {}

  EdgeSlot* get_or_add(int32_t fu, int32_t tu, uint8_t proto, bool* is_new) {
    uint64_t h = mix64((static_cast<uint64_t>(static_cast<uint32_t>(fu)) << 32) ^
                       (static_cast<uint64_t>(static_cast<uint32_t>(tu)) << 8) ^ proto);
    for (uint32_t probe = 0; probe <= mask_; ++probe) {
      EdgeSlot& s = slots_[(h + probe) & mask_];
      if (!s.used) {
        std::memset(&s, 0, sizeof(s));
        s.used = 1;
        s.from_uid = fu;
        s.to_uid = tu;
        s.protocol = proto;
        *is_new = true;
        order_.push_back(&s);
        return &s;
      }
      if (s.from_uid == fu && s.to_uid == tu && s.protocol == proto) {
        *is_new = false;
        return &s;
      }
    }
    return nullptr;
  }

  void clear() {
    for (EdgeSlot* s : order_) s->used = 0;
    order_.clear();
  }

  const std::vector<EdgeSlot*>& order() const { return order_; }

 private:
  uint32_t mask_;
  std::vector<EdgeSlot> slots_;
  std::vector<EdgeSlot*> order_;
};

struct Ingest {
  // SPSC ring
  std::vector<AlzRecord> ring;
  uint32_t ring_mask;
  std::atomic<uint64_t> head{0};  // producer writes
  std::atomic<uint64_t> tail{0};  // consumer reads
  std::atomic<uint64_t> dropped{0};

  // window state
  int64_t window_ms;
  int64_t current_window = INT64_MIN;  // window id (start_ms / window_ms)
  int64_t closed_upto = INT64_MIN;
  uint64_t late_dropped = 0;

  EdgeTable edges;
  NodeTable nodes;
  // persistent node identity (slots stable across windows)
  std::vector<int32_t> node_uids;
  std::vector<uint8_t> node_types;

  Ingest(int64_t wms, uint32_t ring_cap, uint32_t edge_cap, uint32_t node_cap)
      : ring(ring_cap), ring_mask(ring_cap - 1), window_ms(wms),
        edges(edge_cap), nodes(node_cap) {}
};

inline uint32_t next_pow2(uint32_t v) {
  uint32_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

void accumulate(Ingest* ig, const AlzRecord& r) {
  int32_t src = ig->nodes.get_or_add(r.from_uid, r.from_type, &ig->node_uids,
                                     &ig->node_types);
  int32_t dst = ig->nodes.get_or_add(r.to_uid, r.to_type, &ig->node_uids,
                                     &ig->node_types);
  if (src < 0 || dst < 0) return;  // node table full: drop
  bool is_new = false;
  EdgeSlot* e = ig->edges.get_or_add(r.from_uid, r.to_uid, r.protocol, &is_new);
  if (e == nullptr) return;  // edge table full: drop
  if (is_new) {
    e->src_slot = src;
    e->dst_slot = dst;
  }
  e->count += 1;
  e->lat_sum += r.latency_ns;
  if (r.latency_ns > e->lat_max) e->lat_max = r.latency_ns;
  // err5 matches GraphBuilder: (status >= 500) | !completed — status 0 on a
  // completed request is a success for non-HTTP protocols
  if (r.status >= 500 || (r.flags & 0x2)) e->err5 += 1;
  else if (r.status >= 400) e->err4 += 1;
  if (r.flags & 0x1) e->tls_cnt += 1;
}

}  // namespace

extern "C" {

void* alz_create(int64_t window_ms, uint32_t ring_capacity, uint32_t max_edges,
                 uint32_t max_nodes) {
  return new Ingest(window_ms, next_pow2(ring_capacity),
                    next_pow2(max_edges * 2), next_pow2(max_nodes * 2));
}

void alz_destroy(void* p) { delete static_cast<Ingest*>(p); }

// Producer side: push n records; returns how many were accepted (the rest
// are counted dropped — the l7.go:764-770 drop-not-block contract).
uint32_t alz_push(void* p, const AlzRecord* recs, uint32_t n) {
  Ingest* ig = static_cast<Ingest*>(p);
  uint64_t head = ig->head.load(std::memory_order_relaxed);
  uint64_t tail = ig->tail.load(std::memory_order_acquire);
  uint32_t space = static_cast<uint32_t>(ig->ring.size() - (head - tail));
  uint32_t take = n < space ? n : space;
  for (uint32_t i = 0; i < take; ++i) {
    ig->ring[(head + i) & ig->ring_mask] = recs[i];
  }
  ig->head.store(head + take, std::memory_order_release);
  if (take < n) ig->dropped.fetch_add(n - take, std::memory_order_relaxed);
  return take;
}

uint64_t alz_dropped(void* p) {
  Ingest* ig = static_cast<Ingest*>(p);
  return ig->dropped.load(std::memory_order_relaxed) + ig->late_dropped;
}

// Consumer side: drain the ring into the current window's accumulator.
// Returns the window id (start_ms / window_ms) that became ready to close,
// or -2^62 if the current window is still open. Records belonging to a
// newer window than the current roll the window forward; records older
// than a closed window are dropped as late.
int64_t alz_drain(void* p) {
  Ingest* ig = static_cast<Ingest*>(p);
  uint64_t tail = ig->tail.load(std::memory_order_relaxed);
  uint64_t head = ig->head.load(std::memory_order_acquire);
  int64_t ready = INT64_MIN;
  while (tail < head) {
    const AlzRecord& r = ig->ring[tail & ig->ring_mask];
    int64_t w = r.start_time_ms / ig->window_ms;
    if (w <= ig->closed_upto) {
      ig->late_dropped += 1;
    } else if (ig->current_window == INT64_MIN || w == ig->current_window) {
      ig->current_window = w;
      accumulate(ig, r);
    } else if (w > ig->current_window) {
      // window rolls: signal the old one ready and leave this record in
      // the ring for the drain that follows the close
      ready = ig->current_window;
      ig->tail.store(tail, std::memory_order_release);
      return ready;
    } else {
      // w < current_window but > closed_upto: stale but window still open
      accumulate(ig, r);
    }
    ++tail;
  }
  ig->tail.store(tail, std::memory_order_release);
  return ready;
}

int64_t alz_current_window(void* p) {
  return static_cast<Ingest*>(p)->current_window;
}

uint32_t alz_node_count(void* p) {
  return static_cast<uint32_t>(static_cast<Ingest*>(p)->node_uids.size());
}

// Close the current window: export aggregated edges into caller buffers
// (each sized >= max_edges) and advance. Returns the edge count, or -1 if
// buffers are too small. Node tables persist across windows; fetch them
// with alz_export_nodes.
int32_t alz_close_window(void* p, uint32_t buf_cap, int64_t* window_start_ms,
                         int32_t* src, int32_t* dst, uint8_t* protocol,
                         uint64_t* count, uint64_t* lat_sum, uint64_t* lat_max,
                         uint32_t* err5, uint32_t* err4, uint32_t* tls_cnt) {
  Ingest* ig = static_cast<Ingest*>(p);
  const auto& order = ig->edges.order();
  if (order.size() > buf_cap) return -1;
  *window_start_ms = ig->current_window * ig->window_ms;
  int32_t n = 0;
  for (const EdgeSlot* e : order) {
    src[n] = e->src_slot;
    dst[n] = e->dst_slot;
    protocol[n] = e->protocol;
    count[n] = e->count;
    lat_sum[n] = e->lat_sum;
    lat_max[n] = e->lat_max;
    err5[n] = e->err5;
    err4[n] = e->err4;
    tls_cnt[n] = e->tls_cnt;
    ++n;
  }
  ig->edges.clear();
  if (ig->current_window != INT64_MIN) ig->closed_upto = ig->current_window;
  ig->current_window = INT64_MIN;
  return n;
}

uint32_t alz_export_nodes(void* p, uint32_t buf_cap, int32_t* uids, uint8_t* types) {
  Ingest* ig = static_cast<Ingest*>(p);
  uint32_t n = static_cast<uint32_t>(ig->node_uids.size());
  if (n > buf_cap) n = buf_cap;
  std::memcpy(uids, ig->node_uids.data(), n * sizeof(int32_t));
  std::memcpy(types, ig->node_types.data(), n * sizeof(uint8_t));
  return n;
}

}  // extern "C"
